"""kbt-check static analyzer: fixture-driven good/bad snippets per rule,
suppression contract, CLI, and the tier-1 self-enforcement check that keeps
the whole package clean."""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

from kube_batch_tpu.analysis import check_source, run_paths
from kube_batch_tpu.analysis.rules import RULES_BY_ID


def findings_for(src: str, relpath: str):
    return check_source(textwrap.dedent(src), relpath)


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# KBT001 — wall clock outside the Clock seam
# ---------------------------------------------------------------------------


class TestKBT001:
    BAD = """
    import time

    def pace():
        time.sleep(1.0)
        return time.monotonic()
    """

    def test_bad_snippet_triggers_exactly_kbt001(self):
        findings = findings_for(self.BAD, "actions/x.py")
        assert rule_ids(findings) == ["KBT001"]
        assert len(findings) == 2

    def test_from_import_alias_is_caught(self):
        findings = findings_for(
            "from time import sleep as zzz\ndef f():\n    zzz(1)\n",
            "sim/x.py",
        )
        assert rule_ids(findings) == ["KBT001"]

    def test_datetime_now_is_caught(self):
        findings = findings_for(
            "import datetime\ndef f():\n    return datetime.datetime.now()\n",
            "cache/x.py",
        )
        assert rule_ids(findings) == ["KBT001"]

    def test_injected_clock_is_the_sanctioned_path(self):
        good = """
        class S:
            def pace(self):
                t = self.clock.monotonic()
                self.clock.sleep(1.0)
                return t
        """
        assert findings_for(good, "scheduler.py") == []

    def test_out_of_scope_paths_unflagged(self):
        # cmd/ owns real wall-clock concerns (leases, rate limits)
        assert findings_for(self.BAD, "cmd/x.py") == []

    def test_annotation_suppresses(self):
        src = """
        import time

        def f():
            # kbt: allow[KBT001] measures real compute for the bench
            return time.perf_counter()
        """
        assert findings_for(src, "actions/x.py") == []


# ---------------------------------------------------------------------------
# KBT002 — blocking call under a lock
# ---------------------------------------------------------------------------


class TestKBT002:
    def test_sleep_under_lock_triggers(self):
        src = """
        import time

        def take(self):
            with self._lock:
                time.sleep(0.1)
        """
        # KBT002 everywhere; out of KBT001 scope so only the lock rule fires
        findings = findings_for(src, "cmd/server.py")
        assert rule_ids(findings) == ["KBT002"]

    def test_future_result_and_queue_get_under_lock_trigger(self):
        src = """
        def drain(self):
            with self._lock:
                self.future.result()
                item = work_queue.get()
        """
        findings = findings_for(src, "k8s/x.py")
        assert len(findings) == 2 and rule_ids(findings) == ["KBT002"]

    def test_tokenbucket_pattern_is_clean(self):
        src = """
        def take(self):
            with self._lock:
                self._tokens -= 1.0
                wait = max(0.0, -self._tokens / self._qps)
            if wait:
                self._time.sleep(wait)
        """
        assert findings_for(src, "cmd/server.py") == []

    def test_dict_get_under_lock_is_not_blocking(self):
        src = """
        def read(self):
            with self._lock:
                return self.index.get("k")
        """
        assert findings_for(src, "k8s/x.py") == []

    def test_nested_def_body_is_not_under_the_lock(self):
        src = """
        import time

        def sched(self):
            with self._lock:
                def later():
                    time.sleep(1)
                return later
        """
        assert findings_for(src, "cmd/x.py") == []

    def test_non_lock_with_is_ignored(self):
        src = """
        import time

        def f():
            with open("x") as fh:
                time.sleep(1)
                return fh
        """
        assert findings_for(src, "cmd/x.py") == []


# ---------------------------------------------------------------------------
# KBT003 — module-level mutable state in actions/framework
# ---------------------------------------------------------------------------


class TestKBT003:
    def test_module_dict_and_global_write_trigger(self):
        src = """
        last_host_discards = {}

        def execute(ssn):
            global cycle_count
            cycle_count = 1
        """
        findings = findings_for(src, "actions/x.py")
        assert rule_ids(findings) == ["KBT003"]
        assert len(findings) == 2

    def test_constants_and_dunders_are_fine(self):
        src = """
        OVERCOMMIT = {"cpu": 1.2}
        __all__ = ["execute"]
        logger = get_logger("x")
        """
        assert findings_for(src, "framework/x.py") == []

    def test_annotated_registry_is_fine(self):
        src = """
        # kbt: allow[KBT003] import-time registry, read-only after import
        _builders = {}
        """
        assert findings_for(src, "framework/x.py") == []

    def test_out_of_scope_module_state_unflagged(self):
        assert findings_for("cache = {}\n", "plugins/x.py") == []


# ---------------------------------------------------------------------------
# KBT004 — translate-layer fail-open defaults
# ---------------------------------------------------------------------------


class TestKBT004:
    def test_none_fallback_in_value_function_triggers(self):
        src = """
        def node_from(spec):
            if spec.get("kind") == "node":
                return spec["name"]
            return None
        """
        findings = findings_for(src, "k8s/translate.py")
        assert rule_ids(findings) == ["KBT004"]

    def test_empty_collection_fallback_triggers(self):
        src = """
        def terms_from(spec):
            if "terms" in spec:
                return list(spec["terms"])
            return []
        """
        assert rule_ids(findings_for(src, "k8s/translate.py")) == ["KBT004"]

    def test_procedures_with_bare_returns_are_fine(self):
        src = """
        def apply(cache, obj):
            if obj is None:
                return
            cache.add(obj)
        """
        assert findings_for(src, "k8s/translate.py") == []

    def test_fail_closed_sentinel_is_fine(self):
        src = """
        SENTINEL = "__restricted__"

        def node_from(spec):
            if spec.get("kind") == "node":
                return spec["name"]
            return SENTINEL
        """
        assert findings_for(src, "k8s/translate.py") == []

    def test_annotated_default_is_fine(self):
        src = """
        def owner_of(meta):
            for ref in meta.get("ownerReferences") or []:
                return ref["uid"]
            # kbt: allow[KBT004] ownerless pods are a valid spec state
            return None
        """
        assert findings_for(src, "k8s/translate.py") == []

    def test_out_of_scope_none_returns_unflagged(self):
        src = "def f(x):\n    if x:\n        return x\n    return None\n"
        assert findings_for(src, "cache/x.py") == []


# ---------------------------------------------------------------------------
# KBT005 — host-device sync in ops/
# ---------------------------------------------------------------------------


class TestKBT005:
    def test_sync_calls_trigger(self):
        src = """
        import numpy as np

        def solve(x):
            y = np.asarray(x)
            x.block_until_ready()
            return float(y)
        """
        findings = findings_for(src, "ops/x.py")
        assert rule_ids(findings) == ["KBT005"]
        assert len(findings) == 3

    def test_jnp_dispatch_in_python_loop_triggers(self):
        src = """
        import jax.numpy as jnp

        def f(keys):
            total = 0
            for k in keys:
                total = total + jnp.sum(k)
            return total
        """
        assert rule_ids(findings_for(src, "ops/x.py")) == ["KBT005"]

    def test_vectorized_jnp_is_fine(self):
        src = """
        import jax.numpy as jnp

        def f(x):
            return jnp.sum(x, axis=0)
        """
        assert findings_for(src, "ops/x.py") == []

    def test_annotated_trace_time_unroll_is_fine(self):
        src = """
        import jax.numpy as jnp

        def f(xs):
            acc = xs[0]
            for x in xs[1:]:
                # kbt: allow[KBT005] trace-time unroll over a static tuple
                acc = jnp.maximum(acc, x)
            return acc
        """
        assert findings_for(src, "ops/x.py") == []

    def test_out_of_scope_numpy_unflagged(self):
        src = "import numpy as np\ndef f(x):\n    return np.asarray(x)\n"
        assert findings_for(src, "cache/x.py") == []


# ---------------------------------------------------------------------------
# KBT006 — donated-buffer use after donation
# ---------------------------------------------------------------------------


class TestKBT006:
    BAD = """
    import jax

    scatter = jax.jit(lambda d, r: d.at[r].set(0.0), donate_argnums=(0,))

    def refresh(dev, rows):
        out = scatter(dev, rows)
        total = dev.sum()
        return out, total
    """

    def test_read_after_donation_triggers(self):
        findings = findings_for(self.BAD, "api/x.py")
        assert rule_ids(findings) == ["KBT006"]
        assert "donated" in findings[0].message

    def test_rebind_to_result_is_the_sanctioned_shape(self):
        src = """
        import jax

        scatter = jax.jit(lambda d, r: d.at[r].set(0.0), donate_argnums=(0,))

        def refresh(dev, rows):
            dev = scatter(dev, rows)
            return dev.sum()
        """
        assert findings_for(src, "api/x.py") == []

    def test_alias_of_donated_buffer_is_caught(self):
        src = """
        import jax

        scatter = jax.jit(lambda d, r: d.at[r].set(0.0), donate_argnums=(0,))

        def refresh(dev, rows):
            alias = dev
            out = scatter(dev, rows)
            return out, alias.sum()
        """
        assert rule_ids(findings_for(src, "api/x.py")) == ["KBT006"]

    def test_reassignment_clears_the_taint(self):
        src = """
        import jax

        scatter = jax.jit(lambda d, r: d.at[r].set(0.0), donate_argnums=(0,))

        def refresh(dev, rows, host):
            scatter(dev, rows)
            dev = host
            return dev.sum()
        """
        assert findings_for(src, "api/x.py") == []

    def test_factory_returned_donating_callable_is_tracked(self):
        # the api/resident.py shape: a memoized factory returns the
        # donating jitted scatter; calling `_fn()(dev, ...)` donates arg 0
        src = """
        import jax

        _S = None

        def _fn():
            global _S
            if _S is None:
                _S = jax.jit(lambda d, r: d.at[r].set(0.0),
                             donate_argnums=(0,))
            return _S

        def refresh(dev, rows):
            out = _fn()(dev, rows)
            return out, dev.sum()
        """
        assert rule_ids(findings_for(src, "api/x.py")) == ["KBT006"]

    def test_conditional_donate_tuple_still_tracks(self):
        # backend-conditional donation (the resident scatter's CPU gate)
        # folds may-style: a position that CAN donate is tracked
        src = """
        import jax

        donate = () if backend() == "cpu" else (0,)
        scatter = jax.jit(lambda d, r: d.at[r].set(0.0),
                          donate_argnums=donate)

        def refresh(dev, rows):
            out = scatter(dev, rows)
            return out, dev.sum()
        """
        assert rule_ids(findings_for(src, "api/x.py")) == ["KBT006"]

    def test_annotation_suppresses(self):
        src = """
        import jax

        scatter = jax.jit(lambda d, r: d.at[r].set(0.0), donate_argnums=(0,))

        def refresh(dev, rows):
            out = scatter(dev, rows)
            # kbt: allow[KBT006] cpu-only path, donation is a no-op there
            return out, dev.sum()
        """
        assert findings_for(src, "api/x.py") == []

    # ---- one-level interprocedural donation tracking (ROADMAP standing
    # item): a same-module helper that donates its parameter taints its
    # call sites exactly like a direct donating call ------------------------

    def test_helper_donating_its_param_taints_caller(self):
        src = """
        import jax

        scatter = jax.jit(lambda d, r: d.at[r].set(0.0), donate_argnums=(0,))

        def refresh(buf, rows):
            return scatter(buf, rows)

        def cycle(dev, rows):
            out = refresh(dev, rows)
            return out, dev.sum()
        """
        findings = findings_for(src, "api/x.py")
        assert rule_ids(findings) == ["KBT006"]
        assert any("dev" in f.message for f in findings)

    def test_helper_via_factory_form_taints_caller(self):
        # the `_scatter_fn()()` factory form INSIDE the helper — the
        # one-level scan resolves it through the same symbol table
        src = """
        import jax

        _S = None

        def _scatter_fn():
            global _S
            if _S is None:
                _S = jax.jit(lambda d, r: d.at[r].set(0.0),
                             donate_argnums=(0,))
            return _S

        def refresh(buf, rows):
            return _scatter_fn()(buf, rows)

        def cycle(dev, rows):
            out = refresh(dev, rows)
            return out, dev.sum()
        """
        assert rule_ids(findings_for(src, "api/x.py")) == ["KBT006"]

    def test_caller_rebinding_through_helper_is_clean(self):
        src = """
        import jax

        scatter = jax.jit(lambda d, r: d.at[r].set(0.0), donate_argnums=(0,))

        def refresh(buf, rows):
            return scatter(buf, rows)

        def cycle(dev, rows):
            dev = refresh(dev, rows)
            return dev.sum()
        """
        assert findings_for(src, "api/x.py") == []

    def test_helper_not_donating_its_param_is_inert(self):
        # the helper reads its param but never feeds a donated position —
        # its call sites must NOT taint
        src = """
        import jax

        scatter = jax.jit(lambda d, r: d.at[r].set(0.0), donate_argnums=(0,))

        def peek(buf):
            return buf.sum()

        def cycle(dev, rows):
            total = peek(dev)
            return total, dev.sum()
        """
        assert findings_for(src, "api/x.py") == []


# ---------------------------------------------------------------------------
# KBT007 — jit retrace hazards
# ---------------------------------------------------------------------------


class TestKBT007:
    def test_jit_wrapper_in_function_body_triggers(self):
        src = """
        import jax

        def solve(snap):
            fn = jax.jit(lambda s: s * 2)
            return fn(snap)
        """
        findings = findings_for(src, "ops/x.py")
        assert rule_ids(findings) == ["KBT007"]
        assert "fresh compile cache" in findings[0].message

    def test_memoized_wrapper_is_clean(self):
        # the parallel/mesh.py _jit_cache pattern
        src = """
        import jax

        _cache = {}

        def solve(snap, key):
            fn = _cache.get(key)
            if fn is None:
                fn = jax.jit(lambda s: s * 2)
                _cache[key] = fn
            return fn(snap)
        """
        assert findings_for(src, "parallel/x.py") == []

    def test_global_memo_is_clean(self):
        # the api/resident.py _scatter_fn pattern
        src = """
        import jax

        _S = None

        def _fn():
            global _S
            if _S is None:
                _S = jax.jit(lambda d: d * 2)
            return _S
        """
        assert findings_for(src, "api/x.py") == []

    def test_lru_cached_builder_is_clean(self):
        src = """
        import jax
        from functools import lru_cache

        @lru_cache(maxsize=8)
        def builder(key):
            return jax.jit(lambda s: s * 2)
        """
        assert findings_for(src, "parallel/x.py") == []

    def test_unhashable_static_literal_at_call_site_triggers(self):
        src = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("opts",))
        def solve(snap, opts):
            return snap

        def run(snap):
            return solve(snap, opts={"a": 1})
        """
        findings = findings_for(src, "ops/x.py")
        assert rule_ids(findings) == ["KBT007"]
        assert "unhashable" in findings[0].message

    def test_shape_derived_static_arg_triggers(self):
        src = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("n",))
        def solve(snap, n):
            return snap

        def run(snap, xs):
            return solve(snap, n=len(xs))
        """
        findings = findings_for(src, "ops/x.py")
        assert rule_ids(findings) == ["KBT007"]
        assert "shape-derived" in findings[0].message

    def test_namedtuple_static_arg_is_clean(self):
        # the AllocateConfig shape: hashable, stable cache key
        src = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("config",))
        def solve(snap, config):
            return snap

        def run(snap, config):
            return solve(snap, config=config)
        """
        assert findings_for(src, "ops/x.py") == []

    def test_jitted_closure_over_mutable_module_state_triggers(self):
        src = """
        import jax

        # kbt: allow[KBT003] fixture registry
        _weights = {}

        @jax.jit
        def solve(snap):
            return snap * _weights["w"]
        """
        findings = findings_for(src, "ops/x.py")
        assert rule_ids(findings) == ["KBT007"]
        assert "baked in at trace time" in findings[0].message


# ---------------------------------------------------------------------------
# KBT008 — fail-open seam probes in k8s/
# ---------------------------------------------------------------------------


class TestKBT008:
    def test_defaulted_getattr_probe_triggers(self):
        src = """
        def apply(binder, obj):
            getattr(binder, "add_pv", None)(obj)
        """
        findings = findings_for(src, "k8s/x.py")
        assert rule_ids(findings) == ["KBT008"]
        assert "'add_pv'" in findings[0].message

    def test_lambda_default_probe_triggers(self):
        src = """
        def apply(binder, obj):
            getattr(binder, "add_pv", lambda _o: None)(obj)
        """
        assert rule_ids(findings_for(src, "k8s/x.py")) == ["KBT008"]

    def test_two_arg_getattr_is_fine(self):
        # no default: a missing attribute raises — fail closed
        src = """
        def apply(binder, obj):
            getattr(binder, "add_pv")(obj)
        """
        assert findings_for(src, "k8s/x.py") == []

    def test_dispatch_table_get_probe_triggers(self):
        src = """
        def route(handlers, kind, obj):
            handlers.get(kind)(obj)
        """
        assert rule_ids(findings_for(src, "k8s/x.py")) == ["KBT008"]

    def test_out_of_scope_probe_unflagged(self):
        src = """
        def probe(cache):
            return getattr(cache, "flush_binds", None)
        """
        assert findings_for(src, "framework/x.py") == []

    def test_annotated_capability_probe_is_fine(self):
        src = """
        def reconcile(binder):
            # kbt: allow[KBT008] capability probe: absence means no ledger
            pvs = getattr(binder, "pvs", None)
            return pvs
        """
        assert findings_for(src, "k8s/x.py") == []


# ---------------------------------------------------------------------------
# KBT009 — telemetry clock outside metrics-feeding expressions
# ---------------------------------------------------------------------------


class TestKBT009:
    def test_telemetry_value_in_control_flow_triggers(self):
        src = """
        from kube_batch_tpu.utils import telemetry

        def pace(self):
            t0 = telemetry.perf_counter()
            self.work()
            if telemetry.perf_counter() - t0 > 1.0:
                self.abort()
        """
        findings = findings_for(src, "actions/x.py")
        assert rule_ids(findings) == ["KBT009"]

    def test_metrics_feeding_span_is_the_sanctioned_shape(self):
        src = """
        from kube_batch_tpu.utils import telemetry
        from kube_batch_tpu import metrics

        def timed(self):
            t0 = telemetry.perf_counter()
            self.work()
            metrics.observe_e2e_latency(
                (telemetry.perf_counter() - t0) * 1e3
            )
        """
        assert findings_for(src, "actions/x.py") == []

    def test_unused_binding_is_a_dead_wall_clock_read(self):
        src = """
        from kube_batch_tpu.utils import telemetry

        def f(self):
            t0 = telemetry.perf_counter()
            self.work()
        """
        findings = findings_for(src, "framework/x.py")
        assert rule_ids(findings) == ["KBT009"]
        assert "never read" in findings[0].message

    def test_sink_accumulation_is_clean(self):
        # the allocate action's _PhaseMarks shape: the value flows into an
        # ms sink and the next-mark attribute store
        src = """
        from kube_batch_tpu.utils import telemetry

        def mark(self, key):
            now = telemetry.perf_counter()
            self.sink[key] = self.sink.get(key, 0.0) + (now - self.t) * 1e3
            self.t = now
        """
        assert findings_for(src, "actions/x.py") == []

    def test_read_after_branch_join_is_not_dead(self):
        # review-found FP shape: the binding happens in one branch and the
        # read after the join lands on the merge's union cell — the
        # dead-read check must key on the bind SITE, not cell identity
        src = """
        from kube_batch_tpu.utils import telemetry
        from kube_batch_tpu import metrics

        def f(self, cond):
            t0 = 0.0
            if cond:
                t0 = telemetry.perf_counter()
            metrics.observe_e2e_latency(t0)
        """
        assert findings_for(src, "actions/x.py") == []

    def test_loop_carried_read_is_not_dead(self):
        # review-found FP shape: the next iteration reads the previous
        # iteration's binding (two-pass loop walk rebinds the cell)
        src = """
        from kube_batch_tpu.utils import telemetry
        from kube_batch_tpu import metrics

        def f(self, items):
            prev = telemetry.perf_counter()
            for item in items:
                self.work(item)
                metrics.observe_e2e_latency(prev)
                prev = telemetry.perf_counter()
        """
        assert findings_for(src, "actions/x.py") == []

    def test_out_of_scope_unflagged(self):
        src = """
        from kube_batch_tpu.utils import telemetry

        def f():
            t0 = telemetry.perf_counter()
        """
        assert findings_for(src, "testing/x.py") == []


# ---------------------------------------------------------------------------
# KBT010 — host-device sync on resident values in the action layer
# ---------------------------------------------------------------------------


class TestKBT010:
    def test_asarray_on_solve_result_triggers(self):
        src = """
        import numpy as np
        from kube_batch_tpu.ops.assignment import allocate_solve

        def read(snap, config):
            result = allocate_solve(snap, config)
            return np.asarray(result)
        """
        findings = findings_for(src, "actions/x.py")
        # the fixture's bare dispatch also (correctly) lacks a sentinel
        # consumer, so KBT013 fires alongside since the guard-plane PR
        assert rule_ids(findings) == ["KBT010", "KBT013"]

    def test_attribute_of_result_is_still_the_result(self):
        src = """
        import numpy as np
        from kube_batch_tpu.ops.eviction import evict_solve

        def read(snap, config):
            result = evict_solve(snap, config)
            return np.asarray(result.claim_node)
        """
        assert rule_ids(findings_for(src, "actions/x.py")) == [
            "KBT010", "KBT013",  # bare dispatch: no sentinel consumer either
        ]

    def test_device_get_is_always_a_choke_point(self):
        src = """
        import jax

        def read(result):
            return jax.device_get(result.assigned)
        """
        assert rule_ids(findings_for(src, "actions/x.py")) == ["KBT010"]

    def test_asarray_on_host_snapshot_is_fine(self):
        # the flow-awareness KBT005 lacks: host-backed snap reads are free
        src = """
        import numpy as np

        def read(snap):
            return np.asarray(snap.task_job)
        """
        assert findings_for(src, "actions/x.py") == []

    def test_item_on_device_value_triggers(self):
        src = """
        from kube_batch_tpu.ops.assignment import failure_histogram_solve

        def read(snap):
            hist = failure_histogram_solve(snap)
            return hist.item()
        """
        assert rule_ids(findings_for(src, "actions/x.py")) == ["KBT010"]

    def test_taint_survives_branch_merge(self):
        src = """
        import numpy as np
        from kube_batch_tpu.ops.assignment import failure_histogram_solve

        def read(snap, wanted):
            hist = None
            if wanted:
                hist = failure_histogram_solve(snap)
            return np.asarray(hist)
        """
        assert rule_ids(findings_for(src, "actions/x.py")) == ["KBT010"]

    def test_annotation_marks_the_sanctioned_readback(self):
        src = """
        import jax

        def read(result):
            # kbt: allow[KBT010] the cycle's one blocking readback
            return jax.device_get(result.assigned)
        """
        assert findings_for(src, "actions/x.py") == []

    def test_out_of_scope_sync_unflagged(self):
        src = """
        import jax

        def read(result):
            return jax.device_get(result)
        """
        assert findings_for(src, "testing/x.py") == []

    def test_enqueue_gate_solve_is_a_device_source(self):
        # PR 5 dispatch shape: the jitted enqueue admission scan
        src = """
        import numpy as np
        from kube_batch_tpu.ops.admission import enqueue_gate_solve

        def gate(minr, cand, idle, quanta):
            admitted = enqueue_gate_solve(minr, cand, idle, quanta)
            return np.asarray(admitted)
        """
        assert rule_ids(findings_for(src, "actions/x.py")) == ["KBT010"]

    def test_scatter_factory_result_is_a_device_source(self):
        # PR 5 dispatch shape: the per-mesh resident scatter factory form
        # (`_mesh_shard_scatter_fn(mesh)(dev, rows, vals)`)
        src = """
        import numpy as np

        def refresh(mesh, dev, rows, vals):
            dev = _mesh_shard_scatter_fn(mesh)(dev, rows, vals)
            return np.asarray(dev)
        """
        assert rule_ids(findings_for(src, "api/resident.py")) == ["KBT010"]


# ---------------------------------------------------------------------------
# dataflow: the def-use engine itself
# ---------------------------------------------------------------------------


class TestDataflow:
    @staticmethod
    def _run(src: str):
        """Walk `f` in `src` with a tiny taint visitor: `taint(x)` taints
        x's cell, every load of a tainted name is recorded."""
        import ast as _ast

        from kube_batch_tpu.analysis.dataflow import (
            FlowVisitor,
            walk_function,
        )

        tree = _ast.parse(textwrap.dedent(src))
        func = next(n for n in _ast.walk(tree)
                    if isinstance(n, _ast.FunctionDef) and n.name == "f")
        hits = []

        class V(FlowVisitor):
            def on_call(self, ev, env):
                call = ev.node
                if (isinstance(call.func, _ast.Name)
                        and call.func.id == "taint"):
                    for a in call.args:
                        if isinstance(a, _ast.Name) and a.id in env:
                            env[a.id]["t"] = True

            def on_load(self, ev, env):
                if ev.cell is not None and ev.cell.get("t"):
                    hits.append((ev.name, ev.node.lineno))

        walk_function(func, V())
        return hits

    def test_alias_shares_the_cell(self):
        hits = self._run("""
        def f(a):
            b = a
            taint(a)
            return b
        """)
        assert [h[0] for h in hits] == ["b"]

    def test_reassignment_rebinds_to_a_fresh_cell(self):
        hits = self._run("""
        def f(a, c):
            taint(a)
            a = c
            return a
        """)
        assert hits == []

    def test_branch_taint_survives_the_join(self):
        hits = self._run("""
        def f(a, cond):
            if cond:
                taint(a)
            return a
        """)
        assert [h[0] for h in hits] == ["a"]

    def test_clean_rebind_in_one_branch_does_not_launder(self):
        hits = self._run("""
        def f(a, c, cond):
            taint(a)
            if cond:
                a = c
            return a
        """)
        assert [h[0] for h in hits] == ["a"]

    def test_loop_bottom_taint_reaches_the_top(self):
        hits = self._run("""
        def f(a, xs):
            for x in xs:
                y = a + 1
                taint(a)
            return y
        """)
        assert ("a", 4) in hits  # second pass sees the taint

    def test_tuple_unpack_from_call_taints_every_target(self):
        hits = self._run("""
        def f(a):
            taint(a)
            x, y = a
            return x, y
        """)
        names = {h[0] for h in hits}
        assert {"a", "x", "y"} <= names

    def test_match_arm_bodies_are_walked(self):
        # review-found soundness hole: unhandled statement types were
        # silently skipped, blinding every flow rule inside match blocks
        hits = self._run("""
        def f(a, mode):
            taint(a)
            match mode:
                case "x":
                    return a
                case _:
                    return None
        """)
        assert [h[0] for h in hits] == ["a"]

    def test_match_capture_binds_fresh_and_guard_is_a_test(self):
        hits = self._run("""
        def f(a, mode):
            taint(a)
            match mode:
                case str() as a:
                    return a
        """)
        # the capture rebinds `a` to a fresh cell inside the arm
        assert hits == []


# ---------------------------------------------------------------------------
# engine: suppression contract
# ---------------------------------------------------------------------------


class TestSuppressions:
    def test_allow_without_reason_does_not_suppress(self):
        src = """
        import time

        def f():
            return time.time()  # kbt: allow[KBT001]
        """
        findings = findings_for(src, "actions/x.py")
        # the original finding survives AND the empty allow is itself flagged
        assert rule_ids(findings) == ["KBT000", "KBT001"]

    def test_multiline_annotation_block_covers_next_statement(self):
        src = """
        import time

        def f():
            # kbt: allow[KBT001] long explanation of why this wall-clock
            # read is deliberate, spilling onto a second comment line
            return time.time()
        """
        assert findings_for(src, "actions/x.py") == []

    def test_allow_only_suppresses_its_own_rule(self):
        src = """
        import time

        def f(self):
            with self._lock:
                # kbt: allow[KBT002] reason that names the wrong rule
                time.sleep(1)
        """
        findings = findings_for(src, "actions/x.py")
        assert rule_ids(findings) == ["KBT001"]  # KBT002 suppressed, 001 not

    def test_syntax_error_reports_kbt000(self):
        findings = findings_for("def f(:\n", "actions/x.py")
        assert rule_ids(findings) == ["KBT000"]


# ---------------------------------------------------------------------------
# KBT011 — raw urllib / ad-hoc sleep retry loop outside the transport
# ---------------------------------------------------------------------------


class TestKBT011:
    def test_raw_urlopen_in_k8s_triggers(self):
        src = """
        import urllib.request

        def fetch(url):
            with urllib.request.urlopen(url) as r:
                return r.read()
        """
        assert rule_ids(findings_for(src, "k8s/watch.py")) == ["KBT011"]

    def test_from_import_urlopen_is_caught(self):
        src = """
        from urllib.request import urlopen

        def fetch(url):
            return urlopen(url).read()
        """
        assert rule_ids(findings_for(src, "cmd/server.py")) == ["KBT011"]

    def test_sleep_retry_loop_triggers(self):
        src = """
        import time

        def renew(call):
            for attempt in range(5):
                try:
                    return call()
                except OSError:
                    time.sleep(2 ** attempt)
        """
        assert rule_ids(findings_for(src, "k8s/bind.py")) == ["KBT011"]

    def test_transport_module_is_the_sanctioned_home(self):
        src = """
        import time
        import urllib.request

        def call(url, delays):
            for d in delays:
                try:
                    return urllib.request.urlopen(url)
                except OSError:
                    time.sleep(d)
        """
        assert findings_for(src, "k8s/transport.py") == []

    def test_sleep_outside_a_loop_is_not_a_retry(self):
        src = """
        import time

        def settle():
            time.sleep(0.1)
        """
        assert findings_for(src, "k8s/bind.py") == []

    def test_annotation_suppresses(self):
        src = """
        import time

        def sample(frames):
            while frames:
                frames.pop()
                # kbt: allow[KBT011] sampling cadence, not a retry loop
                time.sleep(0.01)
        """
        assert findings_for(src, "cmd/server.py") == []

    def test_out_of_scope_urlopen_unflagged(self):
        src = """
        import urllib.request

        def fetch(url):
            return urllib.request.urlopen(url).read()
        """
        assert findings_for(src, "testing/e2e.py") == []


# ---------------------------------------------------------------------------
# KBT012 — MOVED to tier D: the writeback-stage handoff contract is a
# KBT302 instance now (analysis/races.py); its fixtures live in
# tests/test_races.py::TestKBT302Legacy and `--select KBT012` aliases
# through (TestCli covers the alias).
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# KBT013 — solve dispatch without a sentinel-verdict consumer
# ---------------------------------------------------------------------------


class TestKBT013:
    def test_dispatch_without_consumer_triggers(self):
        src = """
        def execute(ssn, snap, config):
            result, mode, topk, ginfo = dispatch_allocate_solve(
                snap, config, cols=ssn.columns
            )
            return result
        """
        findings = findings_for(src, "actions/x.py")
        assert rule_ids(findings) == ["KBT013"]
        assert "consume" in findings[0].message

    def test_dispatch_with_consumer_is_clean(self):
        src = """
        def execute(ssn, snap, config, gp):
            result, mode, topk, ginfo = dispatch_allocate_solve(
                snap, config, cols=ssn.columns, guard=gp
            )
            if not gp.consume_verdict("allocate", ginfo["engaged"], 0):
                return None
            return result
        """
        assert findings_for(src, "actions/x.py") == []

    def test_direct_evict_solve_without_consumer_triggers(self):
        src = """
        def solve(ssn, snap, config):
            return evict_solve(snap, config)
        """
        assert rule_ids(findings_for(src, "actions/x.py")) == ["KBT013"]

    def test_dispatch_seam_layer_is_exempt(self):
        # dispatch_*-named helpers RETURN the un-consumed sentinel — the
        # rule holds their call sites to the consumer requirement instead
        src = """
        def dispatch_allocate_solve(snap, config):
            return allocate_sentinel_solve(snap, config)
        """
        assert findings_for(src, "actions/x.py") == []

    def test_out_of_scope_unflagged(self):
        src = """
        def probe(snap, config):
            return evict_solve(snap, config)
        """
        assert findings_for(src, "serve/x.py") == []

    def test_annotation_suppresses(self):
        src = """
        def helper(snap, config):
            # kbt: allow[KBT013] read-only diagnostic solve, never bound
            return evict_solve(snap, config)
        """
        assert findings_for(src, "actions/x.py") == []


# ---------------------------------------------------------------------------
# self-enforcement: the package must be clean (tier-1)
# ---------------------------------------------------------------------------


class TestSelfEnforcement:
    def test_package_has_zero_unsuppressed_findings(self):
        findings = run_paths()  # defaults to the kube_batch_tpu tree
        assert findings == [], "\n" + "\n".join(f.render() for f in findings)

    def test_every_rule_has_title_and_grounding_doc(self):
        for rule in RULES_BY_ID.values():
            assert rule.title
            # each rule documents the incident that motivated it
            assert rule.__doc__ and len(rule.__doc__.strip()) > 40

    def test_all_static_rules_are_registered(self):
        # KBT012 migrated to tier D (races.py KBT302) — id retired here,
        # alive as a --select alias
        assert sorted(RULES_BY_ID) == [
            f"KBT{i:03d}" for i in range(1, 15) if i != 12
        ]

    def test_jaxpr_registry_has_zero_unsuppressed_findings(self):
        # tier B self-enforcement: every registered jitted entry point
        # traces clean (no f64 upcast, no in-graph transfer, no host
        # callback, declared donation intact)
        from kube_batch_tpu.analysis.jaxpr_audit import run_audit

        findings = run_audit()
        assert findings == [], "\n" + "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# CLI: exit codes + JSONL
# ---------------------------------------------------------------------------


class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "kube_batch_tpu.analysis", *args],
            capture_output=True, text=True,
            cwd=str(Path(__file__).resolve().parent.parent),
        )

    def test_clean_tree_exits_zero(self):
        proc = self._run("kube_batch_tpu/analysis")
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_findings_exit_nonzero_and_jsonl_parses(self, tmp_path):
        bad = tmp_path / "ops" / "hot.py"
        bad.parent.mkdir()
        bad.write_text("def f(x):\n    x.block_until_ready()\n")
        proc = self._run("--jsonl", str(bad))
        assert proc.returncode == 1
        rows = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
        assert rows and rows[0]["rule"] == "KBT005"
        assert rows[0]["line"] == 2

    def test_select_unknown_rule_is_usage_error(self):
        proc = self._run("--select", "KBT999")
        assert proc.returncode == 2

    def test_nonexistent_path_is_a_finding_not_clean(self):
        # a typo'd CI path must not report clean/exit 0
        proc = self._run("no/such/dir")
        assert proc.returncode == 1
        assert "does not exist" in proc.stdout

    def test_jaxpr_tier_clean_exits_zero(self):
        proc = self._run("--jaxpr-only")
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_jaxpr_select_parity(self):
        # KBT10x ids route to the audit tier; --jsonl shapes match tier A
        proc = self._run("--select", "KBT104", "--jsonl")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        proc = self._run("--select", "KBT999")
        assert proc.returncode == 2

    def test_static_only_select_skips_the_audit_instead_of_gagging_it(
            self, monkeypatch):
        # review finding: `--jaxpr --select KBT001` used to trace every
        # entry point and then discard all audit findings — CI would
        # believe the tier ran while a donation regression passed.  A
        # selection with no audit ids now skips the audit outright
        from kube_batch_tpu.analysis import __main__ as cli
        from kube_batch_tpu.analysis import jaxpr_audit

        def boom(*a, **k):
            raise AssertionError("audit must not run for a static-only select")

        monkeypatch.setattr(jaxpr_audit, "run_audit", boom)
        rc = cli.main(["--jaxpr", "--select", "KBT001",
                       "kube_batch_tpu/analysis"])
        assert rc == 0

    def test_static_only_select_skips_the_hbm_tier_too(self, monkeypatch):
        # same contract for tier C: `--hbm --select KBT001` must not trace
        # the shape ladder only to discard every KBT20x finding
        from kube_batch_tpu.analysis import __main__ as cli
        from kube_batch_tpu.analysis import hbm_audit, jaxpr_audit

        def boom(*a, **k):
            raise AssertionError("a traced tier must not run for a "
                                 "static-only select")

        monkeypatch.setattr(jaxpr_audit, "run_audit", boom)
        monkeypatch.setattr(hbm_audit, "run_hbm_audit", boom)
        rc = cli.main(["--jaxpr", "--hbm", "--select", "KBT001",
                       "kube_batch_tpu/analysis"])
        assert rc == 0

    def test_hbm_select_implies_the_hbm_tier(self, monkeypatch):
        # a KBT20x selection routes to tier C without an explicit --hbm,
        # and skips tiers A and B outright
        from kube_batch_tpu.analysis import __main__ as cli
        from kube_batch_tpu.analysis import hbm_audit, jaxpr_audit

        calls = {}

        def fake_hbm(select=None):
            calls["select"] = select
            return []

        def boom(*a, **k):
            raise AssertionError("tier B must not run for a KBT20x select")

        monkeypatch.setattr(hbm_audit, "run_hbm_audit", fake_hbm)
        monkeypatch.setattr(jaxpr_audit, "run_audit", boom)
        rc = cli.main(["--select", "KBT203"])
        assert rc == 0
        assert calls["select"] == ["KBT203"]

    def test_list_rules_includes_all_tiers(self):
        proc = self._run("--list-rules")
        assert proc.returncode == 0
        assert "KBT010" in proc.stdout and "KBT101" in proc.stdout
        assert "KBT201" in proc.stdout and "KBT204" in proc.stdout


# ---------------------------------------------------------------------------
# KBT014 — span discipline (obs.trace context managers, no clocks in bodies)
# ---------------------------------------------------------------------------


class TestKBT014:
    def test_perf_counter_pair_inside_span_body_flagged(self):
        src = """
        from kube_batch_tpu import metrics
        from kube_batch_tpu.utils import telemetry

        def f(tracer, action):
            with tracer.span("a"):
                t0 = telemetry.perf_counter()
                action()
                metrics.observe_action_latency(
                    "a", (telemetry.perf_counter() - t0) * 1e6)
        """
        findings = findings_for(src, "actions/x.py")
        assert "KBT014" in rule_ids(findings)
        assert sum(1 for f in findings if f.rule == "KBT014") == 2

    def test_raw_time_inside_span_body_flagged(self):
        # serve/ is outside KBT001's scope — the span-body ban still holds
        src = """
        import time

        def f(tracer):
            with tracer.device_span("probe"):
                time.monotonic()
        """
        findings = findings_for(src, "serve/x.py")
        assert rule_ids(findings) == ["KBT014"]

    def test_manual_span_construction_flagged(self):
        src = """
        from kube_batch_tpu.obs.trace import Span

        def f(tracer):
            sp = Span(tracer, "x")
            return sp
        """
        findings = findings_for(src, "cache/x.py")
        assert "KBT014" in rule_ids(findings)

    def test_span_duration_read_after_block_is_the_sanctioned_form(self):
        src = """
        from kube_batch_tpu import metrics

        def f(tracer, action):
            with tracer.span("a") as sp:
                action()
            metrics.observe_action_latency("a", sp.dur_us)
        """
        assert findings_for(src, "scheduler.py") == []

    def test_injected_clock_inside_span_body_is_sanctioned(self):
        src = """
        class S:
            def f(self):
                with self.tracer.span("pace"):
                    t = self.clock.monotonic()
                return t
        """
        assert findings_for(src, "scheduler.py") == []

    def test_out_of_scope_paths_unflagged(self):
        src = """
        from kube_batch_tpu.utils import telemetry

        def f(tracer):
            with tracer.span("a"):
                return telemetry.perf_counter()
        """
        assert findings_for(src, "analysis/x.py") == []

    def test_annotation_suppresses(self):
        src = """
        from kube_batch_tpu.utils import telemetry

        def f(tracer):
            with tracer.span("a"):
                # kbt: allow[KBT014] migration shim measured both ways
                return telemetry.perf_counter()
        """
        assert findings_for(src, "actions/x.py") == []
