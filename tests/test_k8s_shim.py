"""Kubernetes front-end shim: recorded k8s JSON fixtures → framework
objects → a real scheduling cycle (VERDICT r2 missing #2 — the documented,
tested path from real API objects to the cache)."""

import json
import os
import time

import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.resources import GPU, ResourceSpec
from kube_batch_tpu.api.types import PodGroupPhase, PodPhase
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.k8s import (
    RESOURCES,
    WatchAdapter,
    node_from_k8s,
    parse_quantity,
    pdb_from_k8s,
    pod_from_k8s,
    pod_group_from_k8s,
    priority_class_from_k8s,
    queue_from_k8s,
)
from kube_batch_tpu.scheduler import Scheduler

GiB = 1024**3

FIXTURES = json.load(
    open(os.path.join(os.path.dirname(__file__), "fixtures_k8s", "objects.json"))
)


class TestQuantityParsing:
    def test_forms(self):
        assert parse_quantity("100m") == 0.1
        assert parse_quantity("500u") == 5e-4
        assert parse_quantity("50n") == 5e-8
        assert parse_quantity("2") == 2.0
        assert parse_quantity("1Gi") == 2**30
        assert parse_quantity("500Mi") == 500 * 2**20
        assert parse_quantity("2G") == 2e9
        assert parse_quantity("1e3") == 1000.0
        assert parse_quantity(4) == 4.0


class TestTranslation:
    def test_pod_full(self):
        pod = pod_from_k8s(FIXTURES["pod_full"])
        assert pod.key() == "ml/trainer-0"
        assert pod.uid == "8f14e45f-ceea-467f-a0e6-9d8a76b3c001"
        # requests: sum over app containers, k8s units → framework units
        assert pod.requests["cpu"] == 750.0            # 500m + 250m → milli
        assert pod.requests["memory"] == GiB + 512 * 2**20
        assert pod.requests[GPU] == 2000.0             # 2 GPUs → milli
        # init containers: per-dim max
        assert pod.init_requests["cpu"] == 2000.0
        assert pod.init_requests["memory"] == 4 * GiB
        assert pod.group_name == "train-job"
        assert pod.priority == 1000
        assert pod.priority_class == "high-priority"
        assert pod.node_selector == {"accelerator": "tpu"}
        assert pod.host_ports == (18080,)
        assert pod.volume_claims == ("train-data",)
        assert pod.owner == "job-uid-123"
        assert pod.scheduler_name == "volcano"
        assert len(pod.tolerations) == 1 and pod.tolerations[0].key == "dedicated"
        aff = pod.affinity
        assert aff is not None
        assert aff.node_terms == [[("zone", "In", ("us-central1-a",))]]
        assert len(aff.pod_anti_affinity) == 1
        assert aff.pod_anti_affinity[0].match_labels == {"app": "trainer"}
        assert pod.creation_index > 0

    def test_pod_bound(self):
        pod = pod_from_k8s(FIXTURES["pod_bound"])
        assert pod.node_name == "node-a"
        assert pod.phase == PodPhase.RUNNING
        assert pod.affinity is None

    def test_node(self):
        node = node_from_k8s(FIXTURES["node"])
        assert node.name == "node-a"
        assert node.allocatable["cpu"] == 31900.0      # milli
        assert node.allocatable["memory"] == 120 * GiB
        assert node.allocatable["pods"] == 110.0
        assert node.allocatable[GPU] == 8000.0
        assert node.capacity["cpu"] == 32000.0
        assert node.ready and not node.unschedulable
        assert node.conditions == {"MemoryPressure": False, "DiskPressure": False}
        assert len(node.taints) == 1 and node.taints[0].effect == "NoSchedule"

    def test_podgroup(self):
        pg = pod_group_from_k8s(FIXTURES["podgroup"])
        assert pg.key() == "ml/train-job"
        assert pg.min_member == 4
        assert pg.queue == "ml-queue"
        assert pg.phase == PodGroupPhase.PENDING
        assert pg.min_resources == {"cpu": 3000.0, "memory": 6 * GiB}

    def test_queue(self):
        q = queue_from_k8s(FIXTURES["queue"])
        assert q.name == "ml-queue" and q.weight == 4
        assert q.capability["cpu"] == 100_000.0

    def test_priorityclass(self):
        pc = priority_class_from_k8s(FIXTURES["priorityclass"])
        assert pc.name == "high-priority" and pc.value == 1000
        assert not pc.global_default

    def test_pdb(self):
        pdb = pdb_from_k8s(FIXTURES["pdb"])
        assert pdb.min_available == 2 and pdb.owner == "rs-uid-9"

    def test_pdb_percentage_skipped(self):
        obj = {"metadata": {"name": "pct"}, "spec": {"minAvailable": "50%"}}
        assert pdb_from_k8s(obj) is None


def _gang_pod(i: int) -> dict:
    """A member of the train-job gang, derived from the recorded pod."""
    pod = json.loads(json.dumps(FIXTURES["pod_full"]))
    pod["metadata"]["name"] = f"trainer-{i}"
    pod["metadata"]["uid"] = f"trainer-uid-{i}"
    # drop anti-affinity/ports/volumes so 4 members fit one test node
    del pod["spec"]["affinity"]["podAntiAffinity"]
    pod["spec"]["containers"][0]["ports"] = []
    pod["spec"]["volumes"] = []
    return pod


def _make_cache() -> SchedulerCache:
    return SchedulerCache(spec=ResourceSpec(scalar_names=(GPU,)))


class TestEndToEnd:
    def test_watch_replay_to_scheduled_gang(self):
        """Recorded LIST+WATCH events → cache → a real cycle binds the
        gang. The full documented path from k8s API objects to placements."""
        cache = _make_cache()
        adapter = WatchAdapter(cache, api_server="http://unused")
        adapter.replay(
            [("priorityclasses", "ADDED", FIXTURES["priorityclass"]),
             ("queues", "ADDED", FIXTURES["queue"]),
             ("podgroups", "ADDED", FIXTURES["podgroup"]),
             ("nodes", "ADDED", FIXTURES["node"])]
            + [("pods", "ADDED", _gang_pod(i)) for i in range(4)]
        )
        cache.mark_synced()
        assert set(cache.queues) == {"ml-queue"}
        assert "ml/train-job" in cache.jobs
        job = cache.jobs["ml/train-job"]
        assert len(job.tasks) == 4
        assert job.priority == 0  # resolved at session open, not ingest
        # PodGroup arrived Pending-phase → needs enqueue, like the shipped
        # conf (config/kube-batch-tpu-conf.yaml)
        from kube_batch_tpu.framework.conf import parse_scheduler_conf

        conf = parse_scheduler_conf("""
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
""")
        sched = Scheduler(cache, conf=conf)
        sched.run_once()
        cache.flush_binds()
        assert len(cache.binder.binds) == 4
        assert all(n == "node-a" for n in cache.binder.binds.values())
        # the gang rode the toleration through node-a's taint; priority
        # resolved from the PriorityClass during the session
        assert job.priority == 1000
        errs = cache.columns.check_consistency(cache)
        assert not errs, errs[:3]

    def test_watch_stream_factory_start(self):
        """start() with an injected stream seeds every resource and marks
        the cache synced — the informer WaitForCacheSync analog."""
        cache = _make_cache()

        def stream(kind):
            if kind == "nodes":
                return [("ADDED", FIXTURES["node"])]
            if kind == "queues":
                return [("ADDED", FIXTURES["queue"])]
            return []

        adapter = WatchAdapter(
            cache, api_server="http://unused",
            resources=("nodes", "queues"), stream_factory=stream,
        )
        adapter.start()
        assert cache.wait_for_cache_sync()
        assert "node-a" in cache.nodes and "ml-queue" in cache.queues
        adapter.stop()

    def test_bind_evict_writeback(self):
        """K8sBackend POSTs the Binding subresource and DELETEs on evict —
        the egress half of the front end, against a recording fake apiserver."""
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from kube_batch_tpu.api.pod import Pod
        from kube_batch_tpu.k8s.bind import K8sBackend

        calls = []

        class API(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                body = self.rfile.read(int(self.headers["Content-Length"]))
                calls.append(("POST", self.path, json.loads(body)))
                self.send_response(201)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

            def do_DELETE(self):
                calls.append(("DELETE", self.path, None))
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

        srv = ThreadingHTTPServer(("127.0.0.1", 0), API)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            backend = K8sBackend(f"http://127.0.0.1:{srv.server_address[1]}")
            pod = Pod(name="w", namespace="ns", uid="u1")
            backend.bind(pod, "node-a")
            backend.evict(pod)
        finally:
            srv.shutdown()
        method, path, body = calls[0]
        assert (method, path) == ("POST", "/api/v1/namespaces/ns/pods/w/binding")
        assert body["target"] == {"apiVersion": "v1", "kind": "Node",
                                  "name": "node-a"}
        assert calls[1][:2] == ("DELETE", "/api/v1/namespaces/ns/pods/w")

    def test_seed_reconciles_after_relist(self):
        """A re-list (410 recovery) against a populated cache upserts
        instead of duplicating and deletes objects that vanished during the
        disconnect."""
        cache = _make_cache()
        adapter = WatchAdapter(cache, api_server="http://unused")
        pod_a = FIXTURES["pod_bound"]
        pod_b = json.loads(json.dumps(pod_a))
        pod_b["metadata"]["name"] = "web-2"
        pod_b["metadata"]["uid"] = "web-2-uid"
        adapter.replay([
            ("queues", "ADDED", FIXTURES["queue"]),
            ("nodes", "ADDED", FIXTURES["node"]),
            ("pods", "ADDED", pod_a),
            ("pods", "ADDED", pod_b),
        ])
        assert cache.nodes["node-a"].used.milli_cpu == 200.0
        # re-list: web-2 vanished while disconnected; web-1 still there
        listing = {"items": [pod_a], "metadata": {"resourceVersion": "9"}}
        adapter._get_json = lambda path: listing  # transport stub
        rv = adapter._seed("pods")
        assert rv == "9"
        assert "default/web-1" in cache.pods
        assert "default/web-2" not in cache.pods
        assert cache.nodes["node-a"].used.milli_cpu == 100.0
        errs = cache.columns.check_consistency(cache)
        assert not errs, errs[:3]

    def test_seed_reconcile_deletes_gang_pod(self):
        """Reconcile-deletion of a pod carrying a group annotation must
        resolve the REAL job key (via the stored pod object), releasing its
        gang's task and the node accounting."""
        cache = _make_cache()
        adapter = WatchAdapter(cache, api_server="http://unused")
        member = _gang_pod(0)
        member["spec"]["nodeName"] = "node-a"
        member["status"]["phase"] = "Running"
        adapter.replay([
            ("queues", "ADDED", FIXTURES["queue"]),
            ("podgroups", "ADDED", FIXTURES["podgroup"]),
            ("nodes", "ADDED", FIXTURES["node"]),
            ("pods", "ADDED", member),
        ])
        job = cache.jobs["ml/train-job"]
        assert "ml/trainer-0" in job.tasks
        used_before = cache.nodes["node-a"].used.milli_cpu
        assert used_before > 0
        # pod vanished during a watch gap → re-list without it
        adapter._get_json = lambda path: {
            "items": [], "metadata": {"resourceVersion": "5"}
        }
        adapter._seed("pods")
        assert "ml/trainer-0" not in job.tasks
        assert cache.nodes["node-a"].used.milli_cpu == 0.0
        errs = cache.columns.check_consistency(cache)
        assert not errs, errs[:3]

    def test_seed_isolates_bad_objects(self):
        """One unparseable object must not poison the seed."""
        cache = _make_cache()
        adapter = WatchAdapter(cache, api_server="http://unused")
        bad = {"metadata": {"name": "bad"}, "spec": {"containers": [
            {"resources": {"requests": {"cpu": "not-a-quantity"}}}
        ]}}
        adapter._get_json = lambda path: {
            "items": [bad, FIXTURES["pod_bound"]],
            "metadata": {"resourceVersion": "3"},
        }
        adapter._seed("pods")
        assert "default/web-1" in cache.pods

    def test_modify_and_delete_events(self):
        cache = _make_cache()
        adapter = WatchAdapter(cache, api_server="http://unused")
        adapter.replay([
            ("queues", "ADDED", FIXTURES["queue"]),
            ("nodes", "ADDED", FIXTURES["node"]),
            ("pods", "ADDED", FIXTURES["pod_bound"]),
        ])
        assert "default/web-1" in cache.jobs["default/web-1"].tasks
        node = cache.nodes["node-a"]
        assert node.used.milli_cpu == 100.0
        # MODIFIED: pod finishes → accounting released
        done = json.loads(json.dumps(FIXTURES["pod_bound"]))
        done["status"]["phase"] = "Succeeded"
        adapter.replay([("pods", "MODIFIED", done)])
        assert node.used.milli_cpu == 0.0
        # DELETED: pod gone entirely
        adapter.replay([("pods", "DELETED", done)])
        assert "default/web-1" not in cache.pods
        # node cordon + delete
        cordoned = json.loads(json.dumps(FIXTURES["node"]))
        cordoned["spec"]["unschedulable"] = True
        adapter.replay([("nodes", "MODIFIED", cordoned)])
        assert cache.nodes["node-a"].node.unschedulable
        adapter.replay([("nodes", "DELETED", cordoned)])
        assert "node-a" not in cache.nodes
        errs = cache.columns.check_consistency(cache)
        assert not errs, errs[:3]


def _pvc_pod(name: str, claim: str) -> dict:
    """A pod in ml/ carrying one PVC, derived from the recorded pod —
    affinity/ports dropped so volume reachability alone decides the node."""
    pod = json.loads(json.dumps(FIXTURES["pod_full"]))
    pod["metadata"]["name"] = name
    pod["metadata"]["uid"] = f"{name}-uid"
    pod["metadata"]["annotations"].pop(
        "scheduling.k8s.io/group-name", None)
    del pod["spec"]["affinity"]
    pod["spec"]["containers"][0]["ports"] = []
    pod["spec"]["containers"][0]["resources"]["requests"].pop("nvidia.com/gpu")
    pod["spec"]["volumes"] = [
        {"name": "v", "persistentVolumeClaim": {"claimName": claim}}
    ]
    return pod


class TestVolumeK8sMode:
    """VERDICT r4 missing #1: pv/pvc/storageclass flow through the k8s-mode
    watch into a real volume ledger, and volume reachability constrains
    placement (cache.go:189-209,258-269,311-320)."""

    def _node(self, name: str) -> dict:
        node = json.loads(json.dumps(FIXTURES["node"]))
        node["metadata"]["name"] = name
        node["metadata"]["labels"]["kubernetes.io/hostname"] = name
        node["spec"]["taints"] = []
        return node

    # plain pods shadow into the default queue (cache/util.go:42-60),
    # which must exist in the cluster or the job is skipped at session open
    DEFAULT_QUEUE = {"apiVersion": "scheduling.incubator.k8s.io/v1alpha1",
                     "kind": "Queue", "metadata": {"name": "default"},
                     "spec": {"weight": 1}}

    def _cache(self):
        from kube_batch_tpu.cache.volume import K8sPVLedger

        return SchedulerCache(
            spec=ResourceSpec(scalar_names=(GPU,)),
            volume_binder=K8sPVLedger(),
        )

    def test_local_pv_constrains_placement(self):
        """An unbound no-provisioner claim must land on the one node its
        static local PV is reachable from — node-b, never node-a."""
        cache = self._cache()
        adapter = WatchAdapter(cache, api_server="http://unused")
        adapter.replay([
            ("queues", "ADDED", self.DEFAULT_QUEUE),
            ("storageclasses", "ADDED", FIXTURES["storageclass_local"]),
            ("persistentvolumes", "ADDED", FIXTURES["pv_local"]),
            ("persistentvolumeclaims", "ADDED", FIXTURES["pvc_unbound"]),
            ("nodes", "ADDED", self._node("node-a")),
            ("nodes", "ADDED", self._node("node-b")),
            ("pods", "ADDED", _pvc_pod("stateful-1", "train-data")),
        ])
        cache.mark_synced()
        binder = cache.volume_binder
        assert binder.pvs["pv-ssd-b"].node == "node-b"
        assert binder.pvs["pv-ssd-b"].storage_class == "local-ssd"
        assert "ml/train-data" in binder.claims
        sched = Scheduler(cache)
        sched.run_once()
        cache.flush_binds()
        assert cache.binder.binds == {"ml/stateful-1": "node-b"}
        # the ledger binding became durable at dispatch
        assert binder.bound["ml/train-data"] == "pv-ssd-b"
        errs = cache.columns.check_consistency(cache)
        assert not errs, errs[:3]

    def test_dynamic_claim_places_anywhere(self):
        """A claim of a provisioner-backed class is feasible on every node
        (the volume is created after scheduling)."""
        cache = self._cache()
        adapter = WatchAdapter(cache, api_server="http://unused")
        adapter.replay([
            ("queues", "ADDED", self.DEFAULT_QUEUE),
            ("storageclasses", "ADDED", FIXTURES["storageclass_dynamic"]),
            ("persistentvolumeclaims", "ADDED", FIXTURES["pvc_dynamic"]),
            ("nodes", "ADDED", self._node("node-a")),
            ("pods", "ADDED", _pvc_pod("worker-1", "scratch")),
        ])
        cache.mark_synced()
        sched = Scheduler(cache)
        sched.run_once()
        cache.flush_binds()
        assert cache.binder.binds == {"ml/worker-1": "node-a"}

    def test_unknown_claim_fails_placement(self):
        """A pod referencing a PVC the cluster doesn't carry stays Pending
        (FindPodVolumes errors in the reference)."""
        cache = self._cache()
        adapter = WatchAdapter(cache, api_server="http://unused")
        adapter.replay([
            ("queues", "ADDED", self.DEFAULT_QUEUE),
            ("nodes", "ADDED", self._node("node-a")),
            ("pods", "ADDED", _pvc_pod("orphan-1", "no-such-claim")),
        ])
        cache.mark_synced()
        sched = Scheduler(cache)
        sched.run_once()
        cache.flush_binds()
        assert cache.binder.binds == {}

    def test_bound_pvc_pins_node(self):
        """A PVC already bound (spec.volumeName) to a local PV pins its pod
        to that PV's node."""
        cache = self._cache()
        pvc = json.loads(json.dumps(FIXTURES["pvc_unbound"]))
        pvc["spec"]["volumeName"] = "pv-ssd-b"
        pvc["status"]["phase"] = "Bound"
        adapter = WatchAdapter(cache, api_server="http://unused")
        adapter.replay([
            ("queues", "ADDED", self.DEFAULT_QUEUE),
            ("persistentvolumes", "ADDED", FIXTURES["pv_local"]),
            ("persistentvolumeclaims", "ADDED", pvc),
            ("nodes", "ADDED", self._node("node-a")),
            ("nodes", "ADDED", self._node("node-b")),
            ("pods", "ADDED", _pvc_pod("stateful-2", "train-data")),
        ])
        cache.mark_synced()
        sched = Scheduler(cache)
        sched.run_once()
        cache.flush_binds()
        assert cache.binder.binds == {"ml/stateful-2": "node-b"}

    def test_pvc_deletion_reconciles(self):
        """DELETED events and re-list reconciliation drop ledger entries."""
        cache = self._cache()
        adapter = WatchAdapter(cache, api_server="http://unused")
        adapter.replay([
            ("storageclasses", "ADDED", FIXTURES["storageclass_local"]),
            ("persistentvolumes", "ADDED", FIXTURES["pv_local"]),
            ("persistentvolumeclaims", "ADDED", FIXTURES["pvc_unbound"]),
        ])
        binder = cache.volume_binder
        assert binder.pvs and binder.claims and binder.storage_classes
        adapter.replay([
            ("persistentvolumeclaims", "DELETED", FIXTURES["pvc_unbound"]),
            ("persistentvolumes", "DELETED", FIXTURES["pv_local"]),
            ("storageclasses", "DELETED", FIXTURES["storageclass_local"]),
        ])
        assert not binder.pvs and not binder.claims
        assert not binder.storage_classes
        # re-list reconciliation: a vanished PV/PVC disappears from the ledger
        adapter.replay([
            ("persistentvolumes", "ADDED", FIXTURES["pv_local"]),
            ("persistentvolumeclaims", "ADDED", FIXTURES["pvc_unbound"]),
        ])
        adapter._reconcile_deletions("persistentvolumes", [])
        adapter._reconcile_deletions("persistentvolumeclaims", [])
        assert not binder.pvs and not binder.claims

    def test_bind_writes_cluster_side(self):
        """bind_volumes PATCHes the PV claimRef (static) / the PVC
        selected-node annotation (dynamic) through the throttled transport;
        a failed write queues and retries on the next bind."""
        from kube_batch_tpu.cache.volume import (
            K8sPVLedger, SELECTED_NODE_ANNOTATION)

        class StubTransport:
            def __init__(self):
                self.requests = []
                self.fail_next = 0

            def request(self, method, path, body=None, **kw):
                if self.fail_next:
                    self.fail_next -= 1
                    raise OSError("apiserver away")
                self.requests.append((method, path, body))

        class T:  # minimal task
            def __init__(self, name, ns, claims):
                self.uid = f"{ns}/{name}"
                self.pod = type("P", (), {
                    "namespace": ns, "volume_claims": claims})()

        tr = StubTransport()
        led = K8sPVLedger(transport=tr)
        from kube_batch_tpu.k8s.translate import (
            pv_from_k8s, pvc_from_k8s)

        led.add_storage_class("local-ssd", "kubernetes.io/no-provisioner")
        led.add_storage_class("standard", "pd.csi.storage.gke.io")
        led.add_pv(pv_from_k8s(FIXTURES["pv_local"]))
        led.add_pvc(pvc_from_k8s(FIXTURES["pvc_unbound"]))
        led.add_pvc(pvc_from_k8s(FIXTURES["pvc_dynamic"]))

        static = T("s", "ml", ("train-data",))
        led.allocate_volumes(static, "node-b")
        led.bind_volumes(static)
        led.drain_writes()  # cluster writes run off-cycle on a worker
        assert tr.requests[-1][1] == "/api/v1/persistentvolumes/pv-ssd-b"
        assert tr.requests[-1][2]["spec"]["claimRef"]["name"] == "train-data"
        # an unbound PVC MODIFIED event must NOT clear the in-flight binding
        led.add_pvc(pvc_from_k8s(FIXTURES["pvc_unbound"]))
        assert led.bound["ml/train-data"] == "pv-ssd-b"

        dyn = T("d", "ml", ("scratch",))
        led.allocate_volumes(dyn, "node-a")
        tr.fail_next = 1
        led.bind_volumes(dyn)  # PATCH fails -> queued
        led.drain_writes()
        assert led._pending_writes
        # next bind flushes the queue (retry runs before new writes)
        led.bound.pop("ml/train-data")
        led.add_pvc(pvc_from_k8s(FIXTURES["pvc_unbound"]))
        led.allocate_volumes(static, "node-b")
        led.bind_volumes(static)
        led.drain_writes()
        assert not led._pending_writes
        ann = [r for r in tr.requests
               if "persistentvolumeclaims/scratch" in r[1]]
        assert ann and ann[0][2]["metadata"]["annotations"][
            SELECTED_NODE_ANNOTATION] == "node-a"
        led.close()  # bounded pv-writes join (tier-D shutdown discipline)


class TestVolumeIngestSeam:
    """The _volume_ingest dispatcher (KBT008 dogfood, PR 4): a binder
    lacking an ingest method drops the event LOUDLY — one warning per
    (binder type, method), never a silent getattr miss — and a complete
    binder receives every call."""

    def test_missing_method_warns_once_and_does_not_raise(self, caplog):
        import logging

        from kube_batch_tpu.cache.volume import StandalonePVBinder
        from kube_batch_tpu.k8s.translate import (
            _MISSING_INGEST_WARNED,
            apply_event,
        )

        # the standalone ledger has no PVC objects — --master PVC events
        # reaching it are real drops and must be observable
        cache = SchedulerCache(volume_binder=StandalonePVBinder())
        assert not hasattr(cache.volume_binder, "add_pvc")
        _MISSING_INGEST_WARNED.clear()
        with caplog.at_level(logging.WARNING, logger="kube_batch_tpu"):
            apply_event(cache, "persistentvolumeclaims", "ADDED",
                        FIXTURES["pvc_unbound"])
            apply_event(cache, "persistentvolumeclaims", "ADDED",
                        FIXTURES["pvc_dynamic"])
        drops = [r for r in caplog.records if "has no add_pvc" in r.message]
        assert len(drops) == 1  # warn-once per (type, method), not per event
        assert "dropping" in drops[0].message

    def test_complete_binder_receives_the_dispatch(self, caplog):
        import logging

        from kube_batch_tpu.cache.volume import K8sPVLedger
        from kube_batch_tpu.k8s.translate import apply_event

        cache = SchedulerCache(volume_binder=K8sPVLedger())
        with caplog.at_level(logging.WARNING, logger="kube_batch_tpu"):
            apply_event(cache, "persistentvolumeclaims", "ADDED",
                        FIXTURES["pvc_unbound"])
            apply_event(cache, "storageclasses", "ADDED",
                        FIXTURES["storageclass_local"])
            apply_event(cache, "persistentvolumes", "ADDED",
                        FIXTURES["pv_local"])
        assert cache.volume_binder.claims
        assert cache.volume_binder.storage_classes
        assert cache.volume_binder.pvs
        assert not [r for r in caplog.records if "has no " in r.message]

    def test_fake_binder_is_a_complete_silent_seam(self, caplog):
        import logging

        from kube_batch_tpu.k8s.translate import apply_event

        # the default fake implements the full ingest surface as explicit
        # no-ops (cache/interface.py) — no warnings, nothing stored
        cache = SchedulerCache()
        with caplog.at_level(logging.WARNING, logger="kube_batch_tpu"):
            apply_event(cache, "persistentvolumes", "ADDED",
                        FIXTURES["pv_local"])
            apply_event(cache, "persistentvolumeclaims", "DELETED",
                        FIXTURES["pvc_unbound"])
            apply_event(cache, "storageclasses", "DELETED",
                        FIXTURES["storageclass_local"])
        assert not [r for r in caplog.records if "has no " in r.message]
        assert cache.volume_binder.pvs == {}


class TestEventFuzz:
    def test_shuffled_duplicate_events_keep_cache_consistent(self):
        """Watch streams can deliver duplicates and orderings the happy path
        never sees (reconnect races, re-list overlap): random multisets of
        ADDED/MODIFIED/DELETED per object, shuffled, must leave a consistent
        cache that still schedules — duplicate ADDED upserts (informer
        add-or-update semantics), DELETED of unknowns no-ops."""
        import numpy as np

        from kube_batch_tpu.cache.volume import K8sPVLedger
        from kube_batch_tpu.scheduler import Scheduler

        def node(name):
            n = json.loads(json.dumps(FIXTURES["node"]))
            n["metadata"]["name"] = name
            n["spec"]["taints"] = []
            return n

        for seed in range(4):
            rng = np.random.default_rng(seed)
            cache = SchedulerCache(spec=ResourceSpec(scalar_names=(GPU,)),
                                   volume_binder=K8sPVLedger())
            adapter = WatchAdapter(cache, api_server="http://unused")
            objects = (
                [("queues", FIXTURES["queue"]),
                 ("queues", {"metadata": {"name": "default"},
                             "spec": {"weight": 1}}),
                 ("priorityclasses", FIXTURES["priorityclass"]),
                 ("podgroups", FIXTURES["podgroup"]),
                 ("storageclasses", FIXTURES["storageclass_local"]),
                 ("persistentvolumes", FIXTURES["pv_local"]),
                 ("persistentvolumeclaims", FIXTURES["pvc_unbound"]),
                 ("poddisruptionbudgets", FIXTURES["pdb"])]
                + [("nodes", node(f"n{i}")) for i in range(3)]
                + [("pods", _gang_pod(i)) for i in range(4)]
            )
            events = []
            for kind, obj in objects:
                for _ in range(int(rng.integers(1, 4))):
                    events.append((kind, str(rng.choice(
                        ["ADDED", "MODIFIED", "DELETED"])), obj))
            order = rng.permutation(len(events))
            adapter.replay([events[i] for i in order])
            cache.mark_synced()
            sched = Scheduler(cache)
            sched.run_once()
            cache.flush_binds()
            errs = cache.columns.check_consistency(cache)
            assert not errs, (seed, errs[:5])
            # a full re-list (everything as MODIFIED upserts) converges
            adapter.replay([(k, "MODIFIED", o) for k, o in objects])
            sched.run_once()
            cache.flush_binds()
            errs = cache.columns.check_consistency(cache)
            assert not errs, (seed, "after relist", errs[:5])


class TestPvNodeAffinityFailClosed:
    """ADVICE.md #1 regression: a PV whose REQUIRED nodeAffinity terms are
    unrecognized must translate as restrictive (reachable from no node),
    never as node=None (reachable from every node); metadata.name In
    expressions are a recognized single-node pin."""

    @staticmethod
    def _pv(node_affinity):
        spec = {"storageClassName": "local-ssd"}
        if node_affinity is not None:
            spec["nodeAffinity"] = node_affinity
        return {"apiVersion": "v1", "kind": "PersistentVolume",
                "metadata": {"name": "pv-x"}, "spec": spec}

    def test_no_required_affinity_is_reachable_everywhere(self):
        from kube_batch_tpu.k8s.translate import pv_from_k8s

        assert pv_from_k8s(self._pv(None)).node is None

    def test_hostname_in_term_pins_the_node(self):
        from kube_batch_tpu.k8s.translate import pv_from_k8s

        aff = {"required": {"nodeSelectorTerms": [{"matchExpressions": [
            {"key": "kubernetes.io/hostname", "operator": "In",
             "values": ["node-b"]}]}]}}
        assert pv_from_k8s(self._pv(aff)).node == "node-b"

    def test_metadata_name_expression_pins_the_node(self):
        from kube_batch_tpu.k8s.translate import pv_from_k8s

        aff = {"required": {"nodeSelectorTerms": [{"matchExpressions": [
            {"key": "metadata.name", "operator": "In",
             "values": ["node-c"]}]}]}}
        assert pv_from_k8s(self._pv(aff)).node == "node-c"

    def test_metadata_name_match_fields_pin_the_node(self):
        from kube_batch_tpu.k8s.translate import pv_from_k8s

        aff = {"required": {"nodeSelectorTerms": [{"matchFields": [
            {"key": "metadata.name", "operator": "In",
             "values": ["node-d"]}]}]}}
        assert pv_from_k8s(self._pv(aff)).node == "node-d"

    def test_unrecognized_required_terms_fail_closed(self):
        from kube_batch_tpu.cache.volume import K8sPVLedger
        from kube_batch_tpu.k8s.translate import (
            PV_NODE_RESTRICTED_UNKNOWN, pv_from_k8s, pvc_from_k8s)

        aff = {"required": {"nodeSelectorTerms": [{"matchExpressions": [
            {"key": "topology.kubernetes.io/zone", "operator": "In",
             "values": ["us-central1-a"]}]}]}}
        pv = pv_from_k8s(self._pv(aff))
        assert pv.node == PV_NODE_RESTRICTED_UNKNOWN
        # and the ledger treats it as unreachable from every node, so the
        # placement fails instead of landing where the volume can't attach
        led = K8sPVLedger()
        led.add_storage_class("local-ssd", "kubernetes.io/no-provisioner")
        led.add_pv(pv)
        led.add_pvc(pvc_from_k8s({
            "metadata": {"name": "zonal-data", "namespace": "ml"},
            "spec": {"storageClassName": "local-ssd"},
            "status": {"phase": "Pending"},
        }))

        class T:
            uid = "ml/consumer"
            pod = type("P", (), {"namespace": "ml",
                                 "volume_claims": ("zonal-data",)})()

        assert not led.volume_feasible(T(), "node-a")
        assert not led.volume_feasible(T(), "us-central1-a")


class TestPvLedgerRetryQueue:
    """ADVICE.md #2 regression: retry-queue overflow must release the
    dropped claimRef's ledger binding (so it re-derives), and queued
    retries must drain on a timer even when the scheduler goes idle."""

    class _Transport:
        def __init__(self, fail_next=0):
            self.requests = []
            self.fail_next = fail_next

        def request(self, method, path, body=None, **kw):
            if self.fail_next:
                self.fail_next -= 1
                raise OSError("apiserver away")
            self.requests.append((method, path, body))

    @staticmethod
    def _task(name, claims):
        class T:
            uid = f"ml/{name}"
            pod = type("P", (), {"namespace": "ml",
                                 "volume_claims": tuple(claims)})()

        return T()

    def _led(self, transport):
        from kube_batch_tpu.api.pod import (
            PersistentVolume, PersistentVolumeClaim)
        from kube_batch_tpu.cache.volume import K8sPVLedger

        led = K8sPVLedger(transport=transport)
        led.add_storage_class("local-ssd", "kubernetes.io/no-provisioner")
        for pv in ("pv-1", "pv-2"):
            led.add_pv(PersistentVolume(name=pv, node="node-a",
                                        storage_class="local-ssd"))
        for claim in ("c1", "c2"):
            led.add_pvc(PersistentVolumeClaim(name=claim, namespace="ml",
                                              storage_class="local-ssd"))
        return led

    def test_overflow_releases_dropped_bindings(self):
        tr = self._Transport(fail_next=100)  # apiserver down throughout
        led = self._led(tr)
        led.MAX_PENDING_WRITES = 1
        led.RETRY_FLUSH_INTERVAL = 3600.0  # keep the timer out of this test
        t1 = self._task("a", ["c1"])
        led.allocate_volumes(t1, "node-a")
        led.bind_volumes(t1)
        led.drain_writes()
        assert led._pending_writes and "ml/c1" in led.bound
        dropped_pv = led.bound["ml/c1"]
        t2 = self._task("b", ["c2"])
        led.allocate_volumes(t2, "node-a")
        led.bind_volumes(t2)  # retry of c1 fails again, c2 fails → overflow
        led.drain_writes()
        assert len(led._pending_writes) == 1
        # the dropped claimRef's binding is released for re-derivation —
        # before the fix it stayed in `bound` with no queued write forever
        assert "ml/c1" not in led.bound
        assert "ml/c2" in led.bound
        # and the freed PV is claimable again
        t3 = self._task("c", ["c1"])
        led.allocate_volumes(t3, "node-a")
        assert led.reservations[t3.uid]["ml/c1"] == dropped_pv
        led.close()

    def test_idle_timer_flushes_queued_retries(self):
        tr = self._Transport(fail_next=1)
        led = self._led(tr)
        led.RETRY_FLUSH_INTERVAL = 0.05
        t1 = self._task("a", ["c1"])
        led.allocate_volumes(t1, "node-a")
        led.bind_volumes(t1)  # first PATCH fails → queued, timer armed
        led.drain_writes()
        assert led._pending_writes
        # NO further bind_volumes call: the timer alone must drain it
        deadline = time.time() + 5.0
        while led._pending_writes and time.time() < deadline:
            time.sleep(0.02)
        led.drain_writes()
        assert not led._pending_writes
        assert any("persistentvolumes/" in r[1] for r in tr.requests)
        led.close()


class TestPvTopologyAffinity:
    """ROADMAP follow-on to the fail-closed floor: a PV restricted by
    zonal/regional required terms is reachable from every node whose labels
    satisfy the full nodeSelectorTerms (the reference volumebinder's
    behavior) — the PV_NODE_RESTRICTED_UNKNOWN sentinel now only bites when
    the candidate's labels are unknown to the ledger."""

    ZONAL_AFF = {"required": {"nodeSelectorTerms": [{"matchExpressions": [
        {"key": "topology.kubernetes.io/zone", "operator": "In",
         "values": ["us-central1-a"]}]}]}}

    @staticmethod
    def _pv(node_affinity, name="pv-z"):
        spec = {"storageClassName": "local-ssd"}
        if node_affinity is not None:
            spec["nodeAffinity"] = node_affinity
        return {"apiVersion": "v1", "kind": "PersistentVolume",
                "metadata": {"name": name}, "spec": spec}

    @staticmethod
    def _task(uid, claims):
        class T:
            pass

        t = T()
        t.uid = uid
        t.pod = type("P", (), {"namespace": "ml", "volume_claims": tuple(claims)})()
        return t

    def _zonal_ledger(self):
        from kube_batch_tpu.cache.volume import K8sPVLedger
        from kube_batch_tpu.k8s.translate import pv_from_k8s, pvc_from_k8s

        led = K8sPVLedger()
        led.add_storage_class("local-ssd", "kubernetes.io/no-provisioner")
        led.add_pv(pv_from_k8s(self._pv(self.ZONAL_AFF)))
        led.add_pvc(pvc_from_k8s({
            "metadata": {"name": "zonal-data", "namespace": "ml"},
            "spec": {"storageClassName": "local-ssd"},
            "status": {"phase": "Pending"},
        }))
        return led

    def test_translate_carries_full_terms(self):
        from kube_batch_tpu.k8s.translate import (
            PV_NODE_RESTRICTED_UNKNOWN, pv_from_k8s)

        pv = pv_from_k8s(self._pv(self.ZONAL_AFF))
        assert pv.node == PV_NODE_RESTRICTED_UNKNOWN
        assert pv.node_terms == (
            (("topology.kubernetes.io/zone", "In", ("us-central1-a",)),),
        )

    def test_single_node_pin_also_carries_terms(self):
        from kube_batch_tpu.k8s.translate import pv_from_k8s

        aff = {"required": {"nodeSelectorTerms": [{"matchExpressions": [
            {"key": "kubernetes.io/hostname", "operator": "In",
             "values": ["node-b"]}]}]}}
        pv = pv_from_k8s(self._pv(aff))
        assert pv.node == "node-b"
        assert pv.node_terms

    def test_zonal_pv_feasible_on_labeled_in_zone_node_only(self):
        led = self._zonal_ledger()
        led.set_node_labels("node-a", {"topology.kubernetes.io/zone":
                                       "us-central1-a"})
        led.set_node_labels("node-b", {"topology.kubernetes.io/zone":
                                       "us-central1-b"})
        t = self._task("ml/consumer", ["zonal-data"])
        assert led.volume_feasible(t, "node-a")
        assert not led.volume_feasible(t, "node-b")
        # a node the ledger has no labels for stays fail-closed
        assert not led.volume_feasible(t, "node-unknown")

    def test_allocate_and_bind_on_zone_match(self):
        led = self._zonal_ledger()
        led.set_node_labels("node-a", {"topology.kubernetes.io/zone":
                                       "us-central1-a"})
        t = self._task("ml/consumer", ["zonal-data"])
        led.allocate_volumes(t, "node-a")
        led.bind_volumes(t)
        assert led.bound["ml/zonal-data"] == "pv-z"

    def test_deleting_node_labels_fails_closed_again(self):
        led = self._zonal_ledger()
        led.set_node_labels("node-a", {"topology.kubernetes.io/zone":
                                       "us-central1-a"})
        t = self._task("ml/consumer", ["zonal-data"])
        assert led.volume_feasible(t, "node-a")
        led.forget_node_labels("node-a")
        assert not led.volume_feasible(t, "node-a")

    def test_cache_node_ingest_feeds_ledger_labels(self):
        from kube_batch_tpu.api.pod import Node
        from kube_batch_tpu.cache.cache import SchedulerCache

        led = self._zonal_ledger()
        cache = SchedulerCache(volume_binder=led)
        cache.add_node(Node(
            name="node-a",
            allocatable={"cpu": 4000.0},
            labels={"topology.kubernetes.io/zone": "us-central1-a"},
        ))
        t = self._task("ml/consumer", ["zonal-data"])
        assert led.volume_feasible(t, "node-a")
        cache.delete_node("node-a")
        assert not led.volume_feasible(t, "node-a")

    def test_hostname_terms_work_without_label_ingest(self):
        # the kubelet-set hostname label is synthesized, so a multi-host
        # hostname In [...] term works even on ledgers that never saw labels
        led = self._zonal_ledger()
        from kube_batch_tpu.k8s.translate import pv_from_k8s, pvc_from_k8s

        aff = {"required": {"nodeSelectorTerms": [{"matchExpressions": [
            {"key": "kubernetes.io/hostname", "operator": "In",
             "values": ["node-a", "node-b"]}]}]}}
        led.add_pv(pv_from_k8s(self._pv(aff, name="pv-two-hosts")))
        led.add_pvc(pvc_from_k8s({
            "metadata": {"name": "dual", "namespace": "ml"},
            "spec": {"storageClassName": "local-ssd"},
            "status": {"phase": "Pending"},
        }))
        t = self._task("ml/dual-consumer", ["dual"])
        # pin fast path covers node-a (first value); terms cover node-b too
        assert led.volume_feasible(t, "node-a")
        assert led.volume_feasible(t, "node-b")
        assert not led.volume_feasible(t, "node-c")


class TestNodeSelectorTermsMatch:
    """Shared evaluator semantics (api/pod.py): OR across terms, AND within,
    Gt/Lt numeric, unknown operators fail closed."""

    def test_or_across_terms_and_within(self):
        from kube_batch_tpu.api.pod import node_selector_terms_match

        terms = (
            (("zone", "In", ("a",)), ("disk", "In", ("ssd",))),
            (("region", "In", ("r1",)),),
        )
        assert node_selector_terms_match(terms, {"zone": "a", "disk": "ssd"})
        assert node_selector_terms_match(terms, {"region": "r1"})
        assert not node_selector_terms_match(terms, {"zone": "a", "disk": "hdd"})

    def test_exists_notin_gt_lt(self):
        from kube_batch_tpu.api.pod import node_selector_terms_match

        assert node_selector_terms_match(
            ((("gpu", "Exists", ()),),), {"gpu": "1"})
        assert not node_selector_terms_match(
            ((("gpu", "DoesNotExist", ()),),), {"gpu": "1"})
        assert node_selector_terms_match(
            ((("slots", "Gt", ("4",)),),), {"slots": "8"})
        assert not node_selector_terms_match(
            ((("slots", "Lt", ("4",)),),), {"slots": "8"})

    def test_unknown_operator_fails_closed(self):
        from kube_batch_tpu.api.pod import node_selector_terms_match

        assert not node_selector_terms_match(
            ((("zone", "Near", ("a",)),),), {"zone": "a"})


class TestPvAffinityReviewRegressions:
    """Two fail-open holes caught in review of the topology-affinity change:
    a hostname pin AND'd with further requirements must not bypass term
    evaluation, and unlabeled nodes must not satisfy negative operators."""

    def test_pin_with_anded_zone_requirement_does_not_fail_open(self):
        from kube_batch_tpu.cache.volume import K8sPVLedger
        from kube_batch_tpu.k8s.translate import (
            PV_NODE_RESTRICTED_UNKNOWN, pv_from_k8s, pvc_from_k8s)

        # ONE term: hostname In [n1] AND zone In [z1] — conditional pin
        aff = {"required": {"nodeSelectorTerms": [{"matchExpressions": [
            {"key": "kubernetes.io/hostname", "operator": "In",
             "values": ["n1"]},
            {"key": "topology.kubernetes.io/zone", "operator": "In",
             "values": ["z1"]}]}]}}
        pv = pv_from_k8s({"apiVersion": "v1", "kind": "PersistentVolume",
                          "metadata": {"name": "pv-cond"},
                          "spec": {"storageClassName": "local-ssd",
                                   "nodeAffinity": aff}})
        # the pin fast path must NOT claim n1 unconditionally
        assert pv.node == PV_NODE_RESTRICTED_UNKNOWN
        led = K8sPVLedger()
        led.add_storage_class("local-ssd", "kubernetes.io/no-provisioner")
        led.add_pv(pv)
        led.add_pvc(pvc_from_k8s({
            "metadata": {"name": "c", "namespace": "ml"},
            "spec": {"storageClassName": "local-ssd"},
            "status": {"phase": "Pending"},
        }))
        t = TestPvTopologyAffinity._task("ml/x", ["c"])
        # n1 in the WRONG zone: both requirements are AND'd, so infeasible
        led.set_node_labels("n1", {"topology.kubernetes.io/zone": "z2"})
        assert not led.volume_feasible(t, "n1")
        # n1 in the right zone: feasible
        led.set_node_labels("n1", {"topology.kubernetes.io/zone": "z1"})
        assert led.volume_feasible(t, "n1")

    def test_negative_operator_on_unlabeled_node_fails_closed(self):
        from kube_batch_tpu.api.pod import PersistentVolume
        from kube_batch_tpu.cache.volume import K8sPVLedger
        from kube_batch_tpu.k8s.translate import (
            PV_NODE_RESTRICTED_UNKNOWN, pvc_from_k8s)

        led = K8sPVLedger()
        led.add_storage_class("local-ssd", "kubernetes.io/no-provisioner")
        led.add_pv(PersistentVolume(
            name="pv-neg", storage_class="local-ssd",
            node=PV_NODE_RESTRICTED_UNKNOWN,
            node_terms=((("topology.kubernetes.io/zone", "NotIn", ("z1",)),),),
        ))
        led.add_pvc(pvc_from_k8s({
            "metadata": {"name": "c", "namespace": "ml"},
            "spec": {"storageClassName": "local-ssd"},
            "status": {"phase": "Pending"},
        }))
        t = TestPvTopologyAffinity._task("ml/x", ["c"])
        # ledger never saw labels for this node: NotIn must NOT match the
        # absent key (the node may well be IN z1) — fail closed
        assert not led.volume_feasible(t, "mystery-node")
        # with labels ingested the genuine semantics apply
        led.set_node_labels("n-out", {"topology.kubernetes.io/zone": "z2"})
        assert led.volume_feasible(t, "n-out")
        led.set_node_labels("n-in", {"topology.kubernetes.io/zone": "z1"})
        assert not led.volume_feasible(t, "n-in")
