"""Enqueue column gate vs the reference Python walk.

The columnar enqueue (actions/enqueue.py + ops/admission.py) replaces the
per-job walk with vectorized candidates, columnar ordering keys, and a
jitted prefix-scan admission.  These tests build identical clusters twice —
one runs the gate (the default columnar path), the other the retained walk
(`_execute_walk`, the reference oracle) — and assert the promoted podgroup
sets match on the ordering/overcommit edge cases: idle exhaustion mid-walk,
exact-boundary fits, per-queue drain order, the proportion capability veto,
unconditional no-MinResources promotions, and randomized batches.
"""

from __future__ import annotations

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.pod import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    Queue,
)
from kube_batch_tpu.api.types import PodGroupPhase, PodPhase
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.framework.interface import get_action
from kube_batch_tpu.framework.session import close_session, open_session

GiB = float(2 ** 30)


def _build(spec):
    """spec: list of (group_name, queue, min_resources | None).  Returns a
    cache with 2 queues (q0 capability-capped in some tests via the queues
    arg), one 8-cpu node, and one Pending pod per group."""
    queues, groups = spec
    cache = SchedulerCache()
    for q in queues:
        cache.add_queue(q)
    cache.add_node(Node(
        name="n0", allocatable={"cpu": 8000.0, "memory": 8 * GiB,
                                "pods": 110.0},
    ))
    for i, (g, queue, minres) in enumerate(groups):
        cache.add_pod_group(PodGroup(
            name=g, namespace="eq", uid=f"pg-{g}", min_member=1,
            queue=queue, creation_index=i + 1, min_resources=minres,
            phase=PodGroupPhase.PENDING,
        ))
        cache.add_pod(Pod(
            name=f"{g}-0", namespace="eq", uid=f"pod-{g}",
            requests={"cpu": 100.0, "memory": GiB / 8},
            annotations={GROUP_NAME_ANNOTATION: g},
            phase=PodPhase.PENDING,
            creation_index=(i + 1) * 100,
        ))
    return cache


def _phases(cache):
    return {
        uid: (job.pod_group.phase if job.pod_group else None)
        for uid, job in sorted(cache.jobs.items())
    }


def _run(spec, path):
    """One enqueue pass over a fresh cluster; `path` picks the column gate
    (the action's default) or the reference walk oracle."""
    cache = _build(spec)
    conf = load_scheduler_conf(None)
    action = get_action("enqueue")
    ssn = open_session(cache, conf.tiers)
    try:
        if path == "gate":
            action.execute(ssn)
            assert action.last_path == "columnar", action.last_path
        else:
            action._execute_walk(ssn, ssn.columns)
        phases = _phases(cache)
    finally:
        close_session(ssn)
    cache.stop()
    return phases


def _both(spec):
    got = _run(spec, "gate")
    want = _run(spec, "walk")
    assert got == want, f"gate {got} != walk {want}"
    return got


def _q(name, weight=1, capability=None):
    return Queue(name=name, uid=f"uq-{name}", weight=weight,
                 capability=capability)


# idle = 8000 cpu × 1.2 = 9600 cpu (nothing used) / memory 9.6 GiB


def test_no_minres_promotes_even_when_idle_exhausted():
    spec = ([_q("q0")], [
        ("big", "q0", {"cpu": 20000.0}),   # cannot fit ever
        ("free", "q0", None),              # no MinResources → unconditional
    ])
    phases = _both(spec)
    assert phases["eq/big"] == PodGroupPhase.PENDING
    assert phases["eq/free"] == PodGroupPhase.INQUEUE


def test_idle_exhaustion_admits_later_smaller_job():
    # walk order is creation order (same queue, equal priorities): a is
    # admitted (9000 ≤ 9600), b fails (5000 > 600), c still fits (512)
    spec = ([_q("q0")], [
        ("a", "q0", {"cpu": 9000.0}),
        ("b", "q0", {"cpu": 5000.0}),
        ("c", "q0", {"cpu": 512.0}),
    ])
    phases = _both(spec)
    assert phases["eq/a"] == PodGroupPhase.INQUEUE
    assert phases["eq/b"] == PodGroupPhase.PENDING
    assert phases["eq/c"] == PodGroupPhase.INQUEUE


def test_exact_overcommit_boundary_admits():
    # min == 1.2 × total exactly (f32-exact values) — less_equal admits
    spec = ([_q("q0")], [("edge", "q0", {"cpu": 9600.0})])
    phases = _both(spec)
    assert phases["eq/edge"] == PodGroupPhase.INQUEUE


def test_queue_drain_order_shapes_admissions():
    # equal shares → queue_order falls back to the name: q0 drains first
    # and consumes the idle q1's job needed
    spec = ([_q("q0"), _q("q1")], [
        ("q1first", "q1", {"cpu": 4000.0}),
        ("q0a", "q0", {"cpu": 6000.0}),
        ("q0b", "q0", {"cpu": 3000.0}),
    ])
    phases = _both(spec)
    assert phases["eq/q0a"] == PodGroupPhase.INQUEUE
    assert phases["eq/q0b"] == PodGroupPhase.INQUEUE
    assert phases["eq/q1first"] == PodGroupPhase.PENDING


def test_empty_minres_dict_takes_the_budgeted_branch():
    """min_resources == {} is NOT the unconditional branch: the walk routes
    it through JobEnqueueable (zero request — fits, but capability-capped
    queues can veto); the gate must agree (review regression)."""
    spec = ([_q("q0", capability={"cpu": 1000.0})], [
        # 1500 cpu already allocated would be needed to veto a zero
        # request; with nothing allocated the empty dict is admitted —
        # through the budgeted branch on BOTH paths
        ("emptymr", "q0", {}),
        ("nomr", "q0", None),
    ])
    phases = _both(spec)
    assert phases["eq/emptymr"] == PodGroupPhase.INQUEUE
    assert phases["eq/nomr"] == PodGroupPhase.INQUEUE


def test_proportion_capability_vetoes_over_cap_jobs():
    # q0 capped at 1000 cpu: the 2000-cpu MinResources job is not
    # enqueueable regardless of idle; the 500-cpu job passes
    spec = ([_q("q0", capability={"cpu": 1000.0})], [
        ("over", "q0", {"cpu": 2000.0}),
        ("under", "q0", {"cpu": 500.0}),
    ])
    phases = _both(spec)
    assert phases["eq/over"] == PodGroupPhase.PENDING
    assert phases["eq/under"] == PodGroupPhase.INQUEUE


@pytest.mark.parametrize("seed", [0, 11, 29])
def test_randomized_batches_match_walk(seed):
    rng = np.random.default_rng(seed)
    queues = [_q("q0", weight=1), _q("q1", weight=2),
              _q("q2", weight=1, capability={"cpu": 3000.0})]
    groups = []
    for i in range(24):
        minres = None
        if rng.random() < 0.8:
            minres = {"cpu": float(rng.choice([256.0, 1024.0, 4096.0])),
                      "memory": float(rng.choice([GiB / 4, GiB]))}
        groups.append((f"g{i}", f"q{int(rng.integers(3))}", minres))
    _both((queues, groups))


def test_gate_and_walk_promotions_visible_to_allocate():
    """End-to-end: enqueue (gate) then allocate must bind the promoted
    job's pods — the j_sched write-through keeps the same-cycle solve
    seeing the promotion."""
    cache = _build(([_q("q0")], [("go", "q0", {"cpu": 256.0})]))
    conf = load_scheduler_conf(None)
    ssn = open_session(cache, conf.tiers)
    try:
        get_action("enqueue").execute(ssn)
        get_action("allocate").execute(ssn)
    finally:
        close_session(ssn)
    cache.flush_binds()
    assert cache.binder.binds, "promoted job's pod did not bind"
    cache.stop()


# ---------------------------------------------------------------------------
# guard-plane shadow audit of the gate vs the object-walk oracle
# ---------------------------------------------------------------------------


class TestWalkShadowAudit:
    """The sampled shadow audit (guard tier 2) for the enqueue gate: every
    KB_AUDIT_EVERY-th columnar dispatch re-derives the admission through
    the reference object walk over the still-unmutated session and diffs
    decision sets — the ROADMAP standing item's coverage for the gate's
    fallback path."""

    def _session(self, spec):
        cache = _build(spec)
        conf = load_scheduler_conf(None)
        ssn = open_session(cache, conf.tiers)
        return cache, ssn

    def test_audit_matches_on_healthy_columns(self):
        from kube_batch_tpu.guard import guard_of

        spec = ([_q("q0")], [
            ("a", "q0", {"cpu": 1000.0}),
            ("free", "q0", None),          # unconditional promotion
        ])
        cache, ssn = self._session(spec)
        gp = guard_of(cache)
        gp.audit_every = 1  # audit every dispatch
        action = get_action("enqueue")
        a0, m0 = gp.audits_run, gp.audits_mismatched
        try:
            action.execute(ssn)
            assert action.last_path == "columnar"
            phases = _phases(cache)
        finally:
            close_session(ssn)
        assert gp.audits_run == a0 + 1, "the gate dispatch must audit"
        assert gp.audits_mismatched == m0
        assert phases["eq/a"] == PodGroupPhase.INQUEUE
        assert phases["eq/free"] == PodGroupPhase.INQUEUE
        cache.stop()

    def test_corrupted_minres_column_trips_and_walk_decides(self):
        """A corrupted j_minres word makes the gate deny a job the walk
        admits: the audit must trip (mismatch) and the WALK's decisions —
        the oracle — must be the ones applied."""
        from kube_batch_tpu.guard import guard_of

        spec = ([_q("q0")], [("a", "q0", {"cpu": 1000.0})])
        cache, ssn = self._session(spec)
        gp = guard_of(cache)
        gp.audit_every = 1
        job = cache.jobs["eq/a"]
        # the corruption: the device-facing minres row claims 1e9 cpu while
        # the authoritative PodGroup asks 1000 — the gate denies, the walk
        # admits
        cache.columns.j_minres[job._row, 0] = 1e9
        action = get_action("enqueue")
        t0 = gp.trips_total
        try:
            action.execute(ssn)
            assert action.last_path == "columnar"
        finally:
            close_session(ssn)
        assert gp.audits_mismatched >= 1, "divergence must be caught"
        assert gp.trips_total == t0 + 1
        # fail over to the oracle: the walk's admission applied
        assert job.pod_group.phase == PodGroupPhase.INQUEUE
        cache.stop()

    def test_no_audit_when_cadence_not_due(self):
        from kube_batch_tpu.guard import guard_of

        spec = ([_q("q0")], [("a", "q0", {"cpu": 1000.0})])
        cache, ssn = self._session(spec)
        gp = guard_of(cache)
        gp.audit_every = 1000  # far beyond one dispatch
        a0 = gp.audits_run
        try:
            get_action("enqueue").execute(ssn)
        finally:
            close_session(ssn)
        assert gp.audits_run == a0
        cache.stop()
