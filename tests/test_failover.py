"""Warm-standby leader failover: the surviving per-cycle device-resident
cache is revalidated (version token + check_consistency) against the
pod-store rebuild and KEPT — post-failover cycles are bit-exact with the
host columns and pay no cold re-upload; only a failed revalidation
cold-starts. Plus the cmd/server warm-standby re-contend loop."""

from __future__ import annotations

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.pod import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    Queue,
)
from kube_batch_tpu.api.types import PodPhase
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.framework.interface import get_action
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.sim import kubelet as kl
from kube_batch_tpu.testing.synthetic import GiB


def _mk_cache(n_nodes=6):
    cache = SchedulerCache()
    # realistic axis capacities so the scatter-delta path engages (micro
    # columns rightly prefer whole-column uploads) — same sizing rationale
    # as test_snapshot_delta's round-trip test
    cache.columns.reserve(n_tasks=2048, n_nodes=128, n_jobs=512)
    for q in range(2):
        cache.add_queue(Queue(name=f"q{q}", uid=f"uq{q}", weight=q + 1))
    for i in range(n_nodes):
        cache.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": 16000.0, "memory": 64 * GiB, "pods": 110.0},
        ))
    return cache


def _add_gang(cache, serial, size=2):
    g = f"g{serial}"
    cache.add_pod_group(PodGroup(
        name=g, namespace="fo", uid=f"pg-{g}", min_member=size,
        queue=f"q{serial % 2}", creation_index=serial,
    ))
    for k in range(size):
        cache.add_pod(Pod(
            name=f"{g}-{k}", namespace="fo", uid=f"pod-{g}-{k}",
            requests={"cpu": 500.0, "memory": 1 * GiB},
            annotations={GROUP_NAME_ANNOTATION: g},
            phase=PodPhase.PENDING,
            creation_index=serial * 100 + k,
        ))


def _add_gang_cpu(cache, serial, size=2, cpu=500.0):
    """_add_gang with a per-gang cpu request (heterogeneous occupancy for
    the crash-recovery bit-exactness test)."""
    g = f"g{serial}"
    cache.add_pod_group(PodGroup(
        name=g, namespace="fo", uid=f"pg-{g}", min_member=size,
        queue=f"q{serial % 2}", creation_index=serial,
    ))
    for k in range(size):
        cache.add_pod(Pod(
            name=f"{g}-{k}", namespace="fo", uid=f"pod-{g}-{k}",
            requests={"cpu": cpu, "memory": 1 * GiB},
            annotations={GROUP_NAME_ANNOTATION: g},
            phase=PodPhase.PENDING,
            creation_index=serial * 100 + k,
        ))


def _cycle(cache, conf, check_resident=False):
    """One real scheduling cycle; optionally assert the device-resident
    per-cycle columns are bit-exact with the freshly built host columns."""
    from kube_batch_tpu.api.resident import PER_CYCLE_FIELDS

    ssn = open_session(cache, conf.tiers)
    try:
        if check_resident:
            cols = cache.columns
            snap, _meta = cols.device_snapshot(ssn)
            swapped = cols.per_cycle_resident(snap)
            for field in PER_CYCLE_FIELDS:
                host = np.asarray(getattr(snap, field))
                dev = np.asarray(getattr(swapped, field))
                assert np.array_equal(host, dev), (
                    f"device-resident {field} diverged post-failover"
                )
        for name in conf.actions:
            get_action(name).execute(ssn)
    finally:
        close_session(ssn)
    cache.flush_binds()


def _warm_resident(cache, conf, cycles=6):
    """Run enough churny cycles that the per-cycle device cache exists and
    the scatter path has engaged."""
    for i in range(cycles):
        _add_gang(cache, serial=i + 1)
        _cycle(cache, conf)
        # progress some pods so statuses churn
        for key in sorted(cache.pods)[: 2]:
            pod = cache.pods[key]
            if pod.node_name and pod.phase == PodPhase.PENDING:
                kl.set_running(cache, key, pod.node_name)
    rc = cache.columns._per_cycle_dev.get(None)
    assert rc is not None and rc.version > 0
    return rc


class TestWarmStandbyRevalidation:
    def test_warm_failover_keeps_resident_cache_bit_exact(self):
        """The acceptance path: after failover_recover the SAME resident
        cache object serves (compiled executables + buffers kept), the next
        cycle is bit-exact vs the host columns, and its upload counters
        move like any steady-state cycle — NOT like a cold start."""
        conf = load_scheduler_conf(None)
        cache = _mk_cache()
        rc = _warm_resident(cache, conf)

        # baseline: what a normal steady-state cycle adds in full uploads
        # (tiny columns legitimately prefer whole-column re-uploads)
        pre = rc.counters()
        _add_gang(cache, serial=100)
        _cycle(cache, conf)
        steady_delta = rc.counters()["full_uploads"] - pre["full_uploads"]

        report = cache.failover_recover()
        assert report["mode"] == "warm", report
        assert report["resident_tokens"]["single"] > 0
        # identity: the cache OBJECT survived — nothing was recompiled
        assert cache.columns._per_cycle_dev.get(None) is rc

        before = rc.counters()
        _cycle(cache, conf, check_resident=True)
        after = rc.counters()
        post_failover_delta = after["full_uploads"] - before["full_uploads"]
        # the first post-failover cycle costs no more than an ordinary
        # steady-state cycle — and far less than a cold start (which pays
        # one full upload per per-cycle field)
        from kube_batch_tpu.api.resident import PER_CYCLE_FIELDS

        assert post_failover_delta <= steady_delta, (
            f"warm failover re-uploaded: {post_failover_delta} vs "
            f"steady {steady_delta}"
        )
        assert post_failover_delta < len(PER_CYCLE_FIELDS)
        assert cache.columns.check_consistency(cache) == []

    def test_cold_start_for_comparison_re_uploads_everything(self):
        """The cold path the warm standby avoids: dropping residency makes
        the next cycle full-upload every per-cycle field."""
        from kube_batch_tpu.api.resident import PER_CYCLE_FIELDS

        conf = load_scheduler_conf(None)
        cache = _mk_cache()
        _warm_resident(cache, conf)
        cache.columns.drop_resident()
        assert cache.columns._per_cycle_dev == {}
        _cycle(cache, conf, check_resident=True)
        rc = cache.columns._per_cycle_dev.get(None)
        assert rc is not None
        assert rc.counters()["full_uploads"] >= len(PER_CYCLE_FIELDS)

    def test_failed_revalidation_cold_starts(self, monkeypatch):
        conf = load_scheduler_conf(None)
        cache = _mk_cache()
        _warm_resident(cache, conf)
        monkeypatch.setattr(
            cache.columns.__class__, "check_consistency",
            lambda self, c: ["planted inconsistency"],
        )
        report = cache.failover_recover()
        assert report["mode"] == "cold"
        assert report["errors"] == ["planted inconsistency"]
        assert cache.columns._per_cycle_dev == {}

    def test_unsynced_resident_cache_never_survives(self):
        """A resident cache that never synced a snapshot (version token 0)
        has mirrors of unknown provenance — revalidation must drop it."""
        from kube_batch_tpu.api.resident import PerCycleDeviceCache

        cache = _mk_cache()
        cache.columns._per_cycle_dev[None] = PerCycleDeviceCache()
        report = cache.columns.revalidate_resident(cache)
        assert report["mode"] == "cold"
        assert cache.columns._per_cycle_dev == {}

    def test_failover_flushes_quarantine(self):
        """The new leader's rebuilt state supersedes the old reign's
        failure history — shelved tasks get a fresh start."""
        conf = load_scheduler_conf(None)
        cache = _mk_cache()
        _warm_resident(cache, conf)
        cache.resync.poison_after = 1

        class Exploding:
            def bind(self, pod, hostname):
                raise RuntimeError("down")

        cache.binder = Exploding()
        _add_gang(cache, serial=50, size=1)
        _cycle(cache, conf)
        cache.process_resync_tasks()
        cache.process_resync_tasks()
        assert cache.resync.quarantined
        cache.failover_recover()
        assert cache.resync.quarantined == {}


class TestWarmStandbyLoop:
    def test_lost_lease_recovers_and_recontends(self, monkeypatch):
        """run_warm_standby: reign 1 loses the lease (LostLeadership), the
        loop resets the elector, reign 2 recovers through failover_recover
        and schedules again — same process, no crash."""
        from kube_batch_tpu.cmd.leader_election import LostLeadership
        from kube_batch_tpu.cmd.server import run_warm_standby

        cache = _mk_cache()
        recoveries = []
        monkeypatch.setattr(
            cache, "failover_recover",
            lambda: recoveries.append(1) or {"mode": "warm",
                                             "resident_tokens": {},
                                             "errors": []},
        )
        sched = Scheduler(cache, conf=load_scheduler_conf(None),
                          schedule_period=0.0)
        sched.on_cycle_end = sched.stop  # each reign runs exactly one cycle

        class StubElector:
            def __init__(self):
                self.runs = 0
                self.resets = 0

            def run(self, lead, on_stopped_leading=None):
                self.runs += 1
                if self.runs == 1:
                    raise LostLeadership("reign 1 lost the lease")
                lead()

            def reset(self):
                self.resets += 1

        elector = StubElector()
        run_warm_standby(elector, sched, cache, max_takeovers=3)
        assert elector.runs == 2 and elector.resets == 1
        assert recoveries == [1]  # reign 2 recovered before its first cycle

    def test_elector_reset_rearms_for_the_same_process(self, tmp_path):
        from kube_batch_tpu.cmd.leader_election import LeaderElector

        e = LeaderElector(str(tmp_path), identity="a")
        e.release()
        assert e._stop.is_set()
        e.reset()
        assert not e._stop.is_set() and e._renew_thread is None

    def test_scheduler_rearms_after_stop(self):
        """run_forever must be re-enterable after stop() — the standby's
        second reign reuses the same Scheduler object."""
        cache = _mk_cache()
        sched = Scheduler(cache, conf=load_scheduler_conf(None),
                          schedule_period=0.0)
        sched.on_cycle_end = sched.stop
        sched.run_forever()   # reign 1: one cycle then stop
        sched.run_forever()   # reign 2 must actually run, not exit at once
        assert sched._stop    # stopped again via on_cycle_end


@pytest.mark.parametrize("seed", [0])
def test_failover_mid_churn_open_state_matches_full_view(seed):
    """After a failover rebuild, the next session open hands out exactly
    what a from-scratch session_view derives (the delta machinery was
    invalidated by the rebuild, not corrupted by it)."""
    conf = load_scheduler_conf(None)
    cache = _mk_cache()
    _warm_resident(cache, conf)
    cache.failover_recover()
    ssn = open_session(cache, conf.tiers)
    try:
        expected = cache.session_view()
        assert set(ssn.jobs) | {j.uid for j in ssn.gate_dropped_jobs} \
            == set(expected.jobs)
    finally:
        close_session(ssn)


# ==========================================================================
# crash recovery: save → process "restart" → load → warm revalidate
# (guard-plane PR satellite)
# ==========================================================================


class TestCrashRecovery:
    """cache/persistence.py save → a fresh process's load →
    ``failover_recover`` warm revalidation, under randomized churn with
    in-flight binds: the next cycle must be BIT-EXACT against the
    uninterrupted run, and no pod may regress to Pending after an acked
    bind."""

    CONF = None  # shipped 5-action conf (enqueue re-promotes parked jobs)

    @classmethod
    def _conf(cls):
        if cls.CONF is None:
            from kube_batch_tpu.framework.conf import shipped_conf_path

            cls.CONF = load_scheduler_conf(shipped_conf_path())
        return cls.CONF

    def _full_cycle(self, cache):
        conf = self._conf()
        ssn = open_session(cache, conf.tiers)
        ssn.action_names = list(conf.actions)
        try:
            for name in conf.actions:
                get_action(name).execute(ssn)
        finally:
            close_session(ssn)
        # binds stay IN FLIGHT here (async binder pool) — the save must
        # drain them itself so the state file can't miss a just-acked bind

    def _churn(self, cache, rng, serial):
        """One churn step: new gangs with HETEROGENEOUS requests (node
        occupancies then differ everywhere, so scores are strictly
        ordered and no decision ever falls to the row-keyed tie-break —
        the restart's row permutation must not be able to change a
        decision), plus random progressions of bound pods to RUNNING."""
        for g in range(int(rng.integers(1, 3))):
            size = int(rng.integers(1, 4))
            cpu = 300.0 + 97.0 * serial + 31.0 * g
            _add_gang_cpu(cache, serial=serial * 10 + g, size=size, cpu=cpu)
        for key in sorted(cache.pods):
            pod = cache.pods[key]
            if pod.node_name and pod.phase == PodPhase.PENDING and rng.random() < 0.4:
                kl.set_running(cache, key, pod.node_name)

    def test_restart_recovers_bit_exact_with_no_bind_regression(
        self, tmp_path
    ):
        from kube_batch_tpu.cache.persistence import load_state, save_state

        path = str(tmp_path / "state.json")
        rng = np.random.default_rng(7)
        cache_a = _mk_cache()
        for serial in range(1, 6):
            self._churn(cache_a, rng, serial)
            self._full_cycle(cache_a)
        # save mid-stream: binds dispatched by the last cycle are still in
        # flight on the async binder — save_state drains them first
        save_state(cache_a, path)
        acked = {k: p.node_name for k, p in cache_a.pods.items()
                 if p.node_name}
        assert acked, "churn must have produced acked binds"

        # "restart": a brand-new process's cache, re-listed from the state
        # file, then warm-revalidated exactly like the standby takeover
        cache_b = SchedulerCache()
        cache_b.columns.reserve(n_tasks=2048, n_nodes=128, n_jobs=512)
        assert load_state(cache_b, path)
        report = cache_b.failover_recover()
        assert report.get("errors", []) == []

        # no pod regresses to Pending after an acked bind: every acked
        # placement survives the restart with its node intact
        for key, node in acked.items():
            restored = cache_b.pods[key]
            assert restored.node_name == node, (
                f"{key} lost its acked bind across the restart"
            )
        from kube_batch_tpu.api.types import TaskStatus as TS

        for job in cache_b.jobs.values():
            for t in job.tasks.values():
                if t.uid in {cache_a.pods[k].uid for k in acked}:
                    assert t.status != TS.PENDING

        # identical next-cycle input on both sides
        for c in (cache_a, cache_b):
            _add_gang_cpu(c, serial=999, size=2, cpu=777.0)

        # the next cycle's SOLVE INPUT is bit-exact UP TO the row
        # permutation the pod-store rebuild introduces (the row allocator
        # re-deals rows; every per-task column gathered through the
        # uid→row maps must agree exactly)
        conf = self._conf()
        ssn_a = open_session(cache_a, conf.tiers)
        ssn_b = open_session(cache_b, conf.tiers)
        try:
            snap_a, meta_a = cache_a.columns.device_snapshot(ssn_a)
            snap_b, meta_b = cache_b.columns.device_snapshot(ssn_b)
            assert meta_a.n_tasks == meta_b.n_tasks
            row_a = {
                t.pod.uid: r
                for r, t in enumerate(cache_a.columns.task_by_row)
                if t is not None
            }
            row_b = {
                t.pod.uid: r
                for r, t in enumerate(cache_b.columns.task_by_row)
                if t is not None
            }
            assert sorted(row_a) == sorted(row_b)
            uids = sorted(row_a)
            pa = np.asarray([row_a[u] for u in uids])
            pb = np.asarray([row_b[u] for u in uids])
            from kube_batch_tpu.api.types import TaskStatus as TS

            def canon_status(arr):
                # a restored acked bind is BOUND where the uninterrupted
                # process still shows BINDING (its ack just landed) — the
                # documented restart collapse; both are ready/allocated
                # states and decision-equivalent.  PENDING is what must
                # never appear for an acked bind (asserted above).
                out = np.array(arr)
                out[out == int(TS.BINDING)] = int(TS.BOUND)
                return out

            for field in ("task_req", "task_resreq", "task_prio",
                          "task_status", "task_valid", "task_pending",
                          "task_best_effort", "task_creation"):
                a = np.asarray(getattr(snap_a, field))[pa]
                b = np.asarray(getattr(snap_b, field))[pb]
                if field == "task_status":
                    a, b = canon_status(a), canon_status(b)
                assert np.array_equal(a, b), (
                    f"snapshot column {field} diverged across the restart"
                )
            # node columns are permutation-free (insertion order replays)
            for field in ("node_idle", "node_releasing", "node_used",
                          "node_alloc", "node_valid", "node_sched"):
                a = np.asarray(getattr(snap_a, field))
                b = np.asarray(getattr(snap_b, field))
                assert np.array_equal(a, b), (
                    f"snapshot column {field} diverged across the restart"
                )
        finally:
            close_session(ssn_a)
            close_session(ssn_b)

        # and the next cycle's DECISIONS are identical: same binds for the
        # new gang, same post-cycle statuses for every task
        before_a = dict(cache_a.binder.binds)
        self._full_cycle(cache_a)
        cache_a.flush_binds()
        self._full_cycle(cache_b)
        cache_b.flush_binds()
        new_a = {k: v for k, v in cache_a.binder.binds.items()
                 if k not in before_a}
        new_b = dict(cache_b.binder.binds)  # fresh binder: all new
        assert new_a and new_a == new_b
        from kube_batch_tpu.api.types import TaskStatus as TS2

        def canon(st):
            return TS2.BOUND if st == TS2.BINDING else st

        status_a = {
            t.uid: canon(t.status)
            for j in cache_a.jobs.values() for t in j.tasks.values()
        }
        status_b = {
            t.uid: canon(t.status)
            for j in cache_b.jobs.values() for t in j.tasks.values()
        }
        assert status_a == status_b
        assert cache_a.columns.check_consistency(cache_a) == []
        assert cache_b.columns.check_consistency(cache_b) == []
