"""Warm-standby leader failover: the surviving per-cycle device-resident
cache is revalidated (version token + check_consistency) against the
pod-store rebuild and KEPT — post-failover cycles are bit-exact with the
host columns and pay no cold re-upload; only a failed revalidation
cold-starts. Plus the cmd/server warm-standby re-contend loop."""

from __future__ import annotations

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.pod import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    Queue,
)
from kube_batch_tpu.api.types import PodPhase
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.framework.interface import get_action
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.sim import kubelet as kl
from kube_batch_tpu.testing.synthetic import GiB


def _mk_cache(n_nodes=6):
    cache = SchedulerCache()
    # realistic axis capacities so the scatter-delta path engages (micro
    # columns rightly prefer whole-column uploads) — same sizing rationale
    # as test_snapshot_delta's round-trip test
    cache.columns.reserve(n_tasks=2048, n_nodes=128, n_jobs=512)
    for q in range(2):
        cache.add_queue(Queue(name=f"q{q}", uid=f"uq{q}", weight=q + 1))
    for i in range(n_nodes):
        cache.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": 16000.0, "memory": 64 * GiB, "pods": 110.0},
        ))
    return cache


def _add_gang(cache, serial, size=2):
    g = f"g{serial}"
    cache.add_pod_group(PodGroup(
        name=g, namespace="fo", uid=f"pg-{g}", min_member=size,
        queue=f"q{serial % 2}", creation_index=serial,
    ))
    for k in range(size):
        cache.add_pod(Pod(
            name=f"{g}-{k}", namespace="fo", uid=f"pod-{g}-{k}",
            requests={"cpu": 500.0, "memory": 1 * GiB},
            annotations={GROUP_NAME_ANNOTATION: g},
            phase=PodPhase.PENDING,
            creation_index=serial * 100 + k,
        ))


def _cycle(cache, conf, check_resident=False):
    """One real scheduling cycle; optionally assert the device-resident
    per-cycle columns are bit-exact with the freshly built host columns."""
    from kube_batch_tpu.api.resident import PER_CYCLE_FIELDS

    ssn = open_session(cache, conf.tiers)
    try:
        if check_resident:
            cols = cache.columns
            snap, _meta = cols.device_snapshot(ssn)
            swapped = cols.per_cycle_resident(snap)
            for field in PER_CYCLE_FIELDS:
                host = np.asarray(getattr(snap, field))
                dev = np.asarray(getattr(swapped, field))
                assert np.array_equal(host, dev), (
                    f"device-resident {field} diverged post-failover"
                )
        for name in conf.actions:
            get_action(name).execute(ssn)
    finally:
        close_session(ssn)
    cache.flush_binds()


def _warm_resident(cache, conf, cycles=6):
    """Run enough churny cycles that the per-cycle device cache exists and
    the scatter path has engaged."""
    for i in range(cycles):
        _add_gang(cache, serial=i + 1)
        _cycle(cache, conf)
        # progress some pods so statuses churn
        for key in sorted(cache.pods)[: 2]:
            pod = cache.pods[key]
            if pod.node_name and pod.phase == PodPhase.PENDING:
                kl.set_running(cache, key, pod.node_name)
    rc = cache.columns._per_cycle_dev.get(None)
    assert rc is not None and rc.version > 0
    return rc


class TestWarmStandbyRevalidation:
    def test_warm_failover_keeps_resident_cache_bit_exact(self):
        """The acceptance path: after failover_recover the SAME resident
        cache object serves (compiled executables + buffers kept), the next
        cycle is bit-exact vs the host columns, and its upload counters
        move like any steady-state cycle — NOT like a cold start."""
        conf = load_scheduler_conf(None)
        cache = _mk_cache()
        rc = _warm_resident(cache, conf)

        # baseline: what a normal steady-state cycle adds in full uploads
        # (tiny columns legitimately prefer whole-column re-uploads)
        pre = rc.counters()
        _add_gang(cache, serial=100)
        _cycle(cache, conf)
        steady_delta = rc.counters()["full_uploads"] - pre["full_uploads"]

        report = cache.failover_recover()
        assert report["mode"] == "warm", report
        assert report["resident_tokens"]["single"] > 0
        # identity: the cache OBJECT survived — nothing was recompiled
        assert cache.columns._per_cycle_dev.get(None) is rc

        before = rc.counters()
        _cycle(cache, conf, check_resident=True)
        after = rc.counters()
        post_failover_delta = after["full_uploads"] - before["full_uploads"]
        # the first post-failover cycle costs no more than an ordinary
        # steady-state cycle — and far less than a cold start (which pays
        # one full upload per per-cycle field)
        from kube_batch_tpu.api.resident import PER_CYCLE_FIELDS

        assert post_failover_delta <= steady_delta, (
            f"warm failover re-uploaded: {post_failover_delta} vs "
            f"steady {steady_delta}"
        )
        assert post_failover_delta < len(PER_CYCLE_FIELDS)
        assert cache.columns.check_consistency(cache) == []

    def test_cold_start_for_comparison_re_uploads_everything(self):
        """The cold path the warm standby avoids: dropping residency makes
        the next cycle full-upload every per-cycle field."""
        from kube_batch_tpu.api.resident import PER_CYCLE_FIELDS

        conf = load_scheduler_conf(None)
        cache = _mk_cache()
        _warm_resident(cache, conf)
        cache.columns.drop_resident()
        assert cache.columns._per_cycle_dev == {}
        _cycle(cache, conf, check_resident=True)
        rc = cache.columns._per_cycle_dev.get(None)
        assert rc is not None
        assert rc.counters()["full_uploads"] >= len(PER_CYCLE_FIELDS)

    def test_failed_revalidation_cold_starts(self, monkeypatch):
        conf = load_scheduler_conf(None)
        cache = _mk_cache()
        _warm_resident(cache, conf)
        monkeypatch.setattr(
            cache.columns.__class__, "check_consistency",
            lambda self, c: ["planted inconsistency"],
        )
        report = cache.failover_recover()
        assert report["mode"] == "cold"
        assert report["errors"] == ["planted inconsistency"]
        assert cache.columns._per_cycle_dev == {}

    def test_unsynced_resident_cache_never_survives(self):
        """A resident cache that never synced a snapshot (version token 0)
        has mirrors of unknown provenance — revalidation must drop it."""
        from kube_batch_tpu.api.resident import PerCycleDeviceCache

        cache = _mk_cache()
        cache.columns._per_cycle_dev[None] = PerCycleDeviceCache()
        report = cache.columns.revalidate_resident(cache)
        assert report["mode"] == "cold"
        assert cache.columns._per_cycle_dev == {}

    def test_failover_flushes_quarantine(self):
        """The new leader's rebuilt state supersedes the old reign's
        failure history — shelved tasks get a fresh start."""
        conf = load_scheduler_conf(None)
        cache = _mk_cache()
        _warm_resident(cache, conf)
        cache.resync.poison_after = 1

        class Exploding:
            def bind(self, pod, hostname):
                raise RuntimeError("down")

        cache.binder = Exploding()
        _add_gang(cache, serial=50, size=1)
        _cycle(cache, conf)
        cache.process_resync_tasks()
        cache.process_resync_tasks()
        assert cache.resync.quarantined
        cache.failover_recover()
        assert cache.resync.quarantined == {}


class TestWarmStandbyLoop:
    def test_lost_lease_recovers_and_recontends(self, monkeypatch):
        """run_warm_standby: reign 1 loses the lease (LostLeadership), the
        loop resets the elector, reign 2 recovers through failover_recover
        and schedules again — same process, no crash."""
        from kube_batch_tpu.cmd.leader_election import LostLeadership
        from kube_batch_tpu.cmd.server import run_warm_standby

        cache = _mk_cache()
        recoveries = []
        monkeypatch.setattr(
            cache, "failover_recover",
            lambda: recoveries.append(1) or {"mode": "warm",
                                             "resident_tokens": {},
                                             "errors": []},
        )
        sched = Scheduler(cache, conf=load_scheduler_conf(None),
                          schedule_period=0.0)
        sched.on_cycle_end = sched.stop  # each reign runs exactly one cycle

        class StubElector:
            def __init__(self):
                self.runs = 0
                self.resets = 0

            def run(self, lead, on_stopped_leading=None):
                self.runs += 1
                if self.runs == 1:
                    raise LostLeadership("reign 1 lost the lease")
                lead()

            def reset(self):
                self.resets += 1

        elector = StubElector()
        run_warm_standby(elector, sched, cache, max_takeovers=3)
        assert elector.runs == 2 and elector.resets == 1
        assert recoveries == [1]  # reign 2 recovered before its first cycle

    def test_elector_reset_rearms_for_the_same_process(self, tmp_path):
        from kube_batch_tpu.cmd.leader_election import LeaderElector

        e = LeaderElector(str(tmp_path), identity="a")
        e.release()
        assert e._stop.is_set()
        e.reset()
        assert not e._stop.is_set() and e._renew_thread is None

    def test_scheduler_rearms_after_stop(self):
        """run_forever must be re-enterable after stop() — the standby's
        second reign reuses the same Scheduler object."""
        cache = _mk_cache()
        sched = Scheduler(cache, conf=load_scheduler_conf(None),
                          schedule_period=0.0)
        sched.on_cycle_end = sched.stop
        sched.run_forever()   # reign 1: one cycle then stop
        sched.run_forever()   # reign 2 must actually run, not exit at once
        assert sched._stop    # stopped again via on_cycle_end


@pytest.mark.parametrize("seed", [0])
def test_failover_mid_churn_open_state_matches_full_view(seed):
    """After a failover rebuild, the next session open hands out exactly
    what a from-scratch session_view derives (the delta machinery was
    invalidated by the rebuild, not corrupted by it)."""
    conf = load_scheduler_conf(None)
    cache = _mk_cache()
    _warm_resident(cache, conf)
    cache.failover_recover()
    ssn = open_session(cache, conf.tiers)
    try:
        expected = cache.session_view()
        assert set(ssn.jobs) | {j.uid for j in ssn.gate_dropped_jobs} \
            == set(expected.jobs)
    finally:
        close_session(ssn)
