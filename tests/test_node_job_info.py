"""Status-algebra tests for NodeInfo/JobInfo — analog of
api/node_info_test.go and api/job_info_test.go (AddTask/RemoveTask deltas,
status index consistency, gang predicates)."""

import pytest

from kube_batch_tpu.api.job_info import JobInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.pod import Node, Pod, PodGroup
from kube_batch_tpu.api.resources import DEFAULT_SPEC
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.api.types import PodPhase, TaskStatus


def make_node(cpu=4000.0, mem=8 * 2**30, pods=110):
    return NodeInfo(
        Node(name="n1", allocatable={"cpu": cpu, "memory": mem, "pods": pods}), DEFAULT_SPEC
    )


def make_task(name="p1", cpu=1000.0, mem=2**30, phase=PodPhase.RUNNING, node="n1"):
    pod = Pod(name=name, requests={"cpu": cpu, "memory": mem}, phase=phase, node_name=node)
    return TaskInfo(pod, DEFAULT_SPEC)


class TestNodeAlgebra:
    def test_running_task_consumes_idle(self):
        n = make_node()
        t = make_task()
        n.add_task(t)
        assert n.idle.milli_cpu == 3000
        assert n.used.milli_cpu == 1000
        assert n.idle.pods == 109
        n.remove_task(t)
        assert n.idle.milli_cpu == 4000
        assert n.used.milli_cpu == 0

    def test_releasing_task_moves_to_releasing(self):
        # Releasing: Releasing += r; Idle -= r; Used += r (node_info.go:165-193)
        n = make_node()
        t = make_task()
        t.status = TaskStatus.RELEASING
        n.add_task(t)
        assert n.releasing.milli_cpu == 1000
        assert n.idle.milli_cpu == 3000
        assert n.used.milli_cpu == 1000

    def test_pipelined_task_consumes_releasing(self):
        n = make_node()
        victim = make_task("victim")
        victim.status = TaskStatus.RELEASING
        n.add_task(victim)
        incoming = make_task("incoming")
        incoming.status = TaskStatus.PIPELINED
        n.add_task(incoming)
        # pipelined eats the future resources, not idle
        assert n.releasing.milli_cpu == 0
        assert n.idle.milli_cpu == 3000
        assert n.used.milli_cpu == 2000

    def test_update_task_status_via_node(self):
        n = make_node()
        t = make_task()
        n.add_task(t)
        t2 = t.clone()
        t2.status = TaskStatus.RELEASING
        n.update_task(t2)
        assert n.releasing.milli_cpu == 1000
        assert n.used.milli_cpu == 1000

    def test_pending_task_no_accounting(self):
        n = make_node()
        t = make_task(phase=PodPhase.PENDING, node=None)
        n.add_task(t)
        assert n.idle.milli_cpu == 4000 and n.used.milli_cpu == 0


class TestJobInfo:
    def make_job(self, min_member=2):
        pg = PodGroup(name="pg1", min_member=min_member, queue="default")
        return JobInfo("default/pg1", DEFAULT_SPEC, pg)

    def test_status_index_and_aggregates(self):
        j = self.make_job()
        t1 = make_task("p1", phase=PodPhase.RUNNING)
        t2 = make_task("p2", phase=PodPhase.PENDING, node=None)
        j.add_task(t1)
        j.add_task(t2)
        assert j.ready_task_num == 1
        assert j.allocated.milli_cpu == 1000
        assert j.total_request.milli_cpu == 2000
        j.update_task_status(t2, TaskStatus.ALLOCATED)
        assert j.ready_task_num == 2
        assert j.allocated.milli_cpu == 2000
        assert j.ready()

    def test_gang_predicates(self):
        j = self.make_job(min_member=2)
        t1 = make_task("p1", phase=PodPhase.RUNNING)
        j.add_task(t1)
        assert not j.ready()
        t2 = make_task("p2", phase=PodPhase.PENDING, node=None)
        j.add_task(t2)
        j.update_task_status(t2, TaskStatus.PIPELINED)
        assert not j.ready()
        assert j.pipelined()  # ready + waiting >= minAvailable (job_info.go:383-418)

    def test_delete_task(self):
        j = self.make_job()
        t1 = make_task("p1", phase=PodPhase.RUNNING)
        j.add_task(t1)
        j.delete_task(t1)
        assert j.ready_task_num == 0
        assert j.total_request.milli_cpu == 0
        assert len(j.tasks) == 0

    def test_clone_is_deep(self):
        j = self.make_job()
        t1 = make_task("p1", phase=PodPhase.RUNNING)
        j.add_task(t1)
        c = j.clone()
        c.update_task_status(list(c.tasks.values())[0], TaskStatus.RELEASING)
        assert j.ready_task_num == 1  # original untouched
        assert c.ready_task_num == 0

    def test_best_effort_task(self):
        t = TaskInfo(Pod(name="be", requests={}), DEFAULT_SPEC)
        assert t.best_effort
        assert not make_task().best_effort

    def test_init_resreq_max(self):
        pod = Pod(
            name="p", requests={"cpu": 500}, init_requests={"cpu": 2000, "memory": 100}
        )
        t = TaskInfo(pod, DEFAULT_SPEC)
        assert t.resreq.milli_cpu == 500
        assert t.init_resreq.milli_cpu == 2000
        assert t.init_resreq.memory == 100


class TestReviewRegressions:
    """Fidelity fixes found in review against the reference sources."""

    def test_succeeded_counts_toward_ready(self):
        # job_info.go ReadyTaskNum counts AllocatedStatus + Succeeded
        pg = PodGroup(name="pg2", min_member=3, queue="default")
        j = JobInfo("default/pg2", DEFAULT_SPEC, pg)
        for i, phase in enumerate([PodPhase.RUNNING, PodPhase.RUNNING, PodPhase.SUCCEEDED]):
            j.add_task(make_task(f"t{i}", phase=phase))
        assert j.ready_task_num == 3
        assert j.ready()

    def test_valid_task_num_excludes_releasing(self):
        # job_info.go ValidTaskNum: AllocatedStatus+Succeeded+Pipelined+Pending
        pg = PodGroup(name="pg3", min_member=2, queue="default")
        j = JobInfo("default/pg3", DEFAULT_SPEC, pg)
        t1 = make_task("a", phase=PodPhase.RUNNING)
        t2 = make_task("b", phase=PodPhase.SUCCEEDED)
        t3 = make_task("c", phase=PodPhase.RUNNING)
        j.add_task(t1)
        j.add_task(t2)
        j.add_task(t3)
        j.update_task_status(t3, TaskStatus.RELEASING)
        assert j.valid_task_num == 2

    def test_set_node_replays_tasks(self):
        # node_info.go SetNode: pods ingested before their node must be
        # re-accounted once the node arrives
        n = NodeInfo(None, DEFAULT_SPEC)
        t = make_task("early", phase=PodPhase.RUNNING)
        n.add_task(t)
        assert n.used.milli_cpu == 0  # no node yet, no accounting
        n.set_node(Node(name="n1", allocatable={"cpu": 4000, "memory": 8 * 2**30, "pods": 110}))
        assert n.used.milli_cpu == 1000
        assert n.idle.milli_cpu == 3000

    def test_overcommitted_node_goes_out_of_sync(self):
        """node_info.go:110-134 setNodeState: used > allocatable ⇒ NotReady
        with reason OutOfSync, which excludes the node from snapshots. The
        entry paths are set_node replays: pods ingested before a too-small
        node, or a node shrinking below its usage."""
        # pods before node, over-summing the node that then arrives
        n = NodeInfo(None, DEFAULT_SPEC)
        n.add_task(make_task("a", cpu=3000.0))
        n.add_task(make_task("b", cpu=3000.0))
        n.set_node(Node(name="n1", allocatable={
            "cpu": 4000.0, "memory": 8 * 2**30, "pods": 110}))
        assert n.state == "OutOfSync"
        assert not n.ready
        assert n.idle.milli_cpu == 0  # clamped, never negative

        # node shrinking below current usage, then growing back
        n2 = make_node(cpu=4000.0)
        n2.add_task(make_task("a", cpu=3000.0))
        assert n2.state == "Ready" and n2.ready
        n2.set_node(Node(name="n1", allocatable={
            "cpu": 2000.0, "memory": 8 * 2**30, "pods": 110}))
        assert n2.state == "OutOfSync" and not n2.ready
        n2.set_node(Node(name="n1", allocatable={
            "cpu": 8000.0, "memory": 8 * 2**30, "pods": 110}))
        assert n2.ready and n2.idle.milli_cpu == 5000

    def test_node_holds_task_copy(self):
        # node_info.go:165-168: caller-side status mutation must not
        # desynchronize the node's reversal algebra
        n = make_node()
        t = make_task()
        n.add_task(t)
        t.status = TaskStatus.RELEASING  # mutate caller's object
        n.remove_task(t)  # reverses under the stored (RUNNING) status
        assert n.idle.milli_cpu == 4000
        assert n.used.milli_cpu == 0
        assert n.releasing.milli_cpu == 0

    def test_deleting_terminal_pod_keeps_status(self):
        # helpers.go getTaskStatus: deletion override only for Running/Pending
        pod = Pod(name="done", requests={"cpu": 100}, phase=PodPhase.SUCCEEDED,
                  node_name="n1", deleting=True)
        assert TaskInfo(pod, DEFAULT_SPEC).status == TaskStatus.SUCCEEDED
        pod2 = Pod(name="dying", requests={"cpu": 100}, phase=PodPhase.RUNNING,
                   node_name="n1", deleting=True)
        assert TaskInfo(pod2, DEFAULT_SPEC).status == TaskStatus.RELEASING
