"""L0/L10 layer tests: options defaults (options_test.go:51), the HTTP
admin/ingest API (the informer + CLI seam), leader election, serialization
round-trips, and the queue CLI against a live server."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from kube_batch_tpu.api import serialize
from kube_batch_tpu.api.pod import (
    GROUP_NAME_ANNOTATION,
    Affinity,
    Node,
    Pod,
    PodGroup,
    Queue,
    Taint,
    Toleration,
)
from kube_batch_tpu.api.types import PodPhase
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.cli import queue as queue_cli
from kube_batch_tpu.cmd import options
from kube_batch_tpu.cmd.leader_election import LeaderElector
from kube_batch_tpu.cmd.server import AdminServer
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.scheduler import Scheduler
from tests.fixtures import build_node, build_pod


def _get(port: int, path: str):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        body = r.read()
        ctype = r.headers.get("Content-Type", "")
        return json.loads(body) if "json" in ctype else body.decode()


def _post_method(port: int, path: str, obj, method: str):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(obj).encode(),
        method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=5) as r:
        return json.loads(r.read())


def _post(port: int, path: str, obj):
    return _post_method(port, path, obj, "POST")


class TestOptions:
    def test_defaults(self):
        opt = options.parse([])
        assert opt.scheduler_name == "volcano"
        assert opt.schedule_period == 1.0
        assert opt.default_queue == "default"
        assert opt.enable_leader_election is False
        assert opt.listen_address == ":8080"
        assert opt.enable_priority_class is True
        assert opt.kube_api_qps == 50.0
        assert opt.kube_api_burst == 100

    def test_leader_election_requires_namespace(self):
        opt = options.parse(["--leader-elect"])
        with pytest.raises(ValueError):
            opt.check_option_or_die()

    def test_flag_parse(self):
        opt = options.parse(
            ["--scheduler-name", "kb", "--schedule-period", "0.5",
             "--listen-address", "127.0.0.1:9999"]
        )
        assert opt.scheduler_name == "kb"
        assert opt.schedule_period == 0.5
        assert opt.listen_host_port == ("127.0.0.1", 9999)

    def test_malformed_listen_address_rejected(self):
        opt = options.parse(["--listen-address", "localhost"])
        with pytest.raises(ValueError):
            opt.check_option_or_die()
        opt = options.parse(["--listen-address", "[::]:8080"])
        assert opt.listen_host_port == ("::", 8080)

    def test_priority_class_toggle(self):
        from kube_batch_tpu.api.pod import PriorityClass
        cache = SchedulerCache(resolve_priority=False)
        cache.add_priority_class(PriorityClass(name="high", value=100))
        assert cache.priority_classes == {}
        pod = build_pod("default", "p", None, PodPhase.PENDING,
                        {"cpu": 100.0}, priority_class="high")
        cache.add_pod(pod)
        assert pod.priority == 0


class TestSerialize:
    def test_pod_round_trip(self):
        pod = Pod(
            name="p1", requests={"cpu": 1000, "memory": 1 << 30},
            annotations={GROUP_NAME_ANNOTATION: "pg1"},
            tolerations=[Toleration(key="k", operator="Exists")],
            affinity=Affinity(node_terms=[[("zone", "In", ("a", "b"))]]),
            host_ports=(8080,),
        )
        back = serialize.pod_from_dict(serialize.pod_to_dict(pod))
        assert back.key() == pod.key()
        assert back.requests == pod.requests
        assert back.group_name == "pg1"
        assert back.tolerations[0].operator == "Exists"
        assert back.affinity.node_terms == [[("zone", "In", ("a", "b"))]]
        assert back.host_ports == (8080,)

    def test_pod_affinity_round_trip(self):
        from kube_batch_tpu.api.pod import PodAffinityTerm
        pod = Pod(
            name="p2",
            affinity=Affinity(
                pod_affinity=[PodAffinityTerm(match_labels={"app": "db"})],
                pod_anti_affinity=[
                    PodAffinityTerm(match_labels={"app": "w"}, topology_key="zone")
                ],
            ),
        )
        back = serialize.pod_from_dict(serialize.pod_to_dict(pod))
        assert back.affinity.pod_affinity[0].match_labels == {"app": "db"}
        assert back.affinity.pod_anti_affinity[0].topology_key == "zone"

    def test_node_round_trip(self):
        node = Node(name="n1", allocatable={"cpu": 4000},
                    taints=[Taint(key="t", effect="NoSchedule")],
                    labels={"zone": "a"})
        back = serialize.node_from_dict(serialize.node_to_dict(node))
        assert back.name == "n1" and back.taints[0].key == "t"
        assert back.labels == {"zone": "a"}

    def test_pod_group_round_trip(self):
        pg = PodGroup(name="pg1", min_member=3, queue="q1")
        back = serialize.pod_group_from_dict(serialize.pod_group_to_dict(pg))
        assert back.min_member == 3 and back.queue == "q1"
        assert back.phase is None


class TestAdminServer:
    @pytest.fixture()
    def server(self):
        cache = SchedulerCache()
        srv = AdminServer(cache, port=0)
        srv.start()
        yield cache, srv
        srv.stop()

    def test_health_version_metrics(self, server):
        _, srv = server
        assert _get(srv.port, "/healthz") == "ok"
        assert "kube-batch-tpu" in _get(srv.port, "/version")
        assert "volcano_e2e_scheduling_latency_milliseconds" in _get(srv.port, "/metrics")

    def test_ingest_schedule_and_read_back(self, server):
        cache, srv = server
        _post(srv.port, "/v1/queues", {"name": "default", "weight": 1})
        _post(srv.port, "/v1/nodes", serialize.node_to_dict(build_node("n1")))
        _post(srv.port, "/v1/podgroups",
              serialize.pod_group_to_dict(PodGroup(name="pg1", min_member=1)))
        _post(srv.port, "/v1/pods", serialize.pod_to_dict(
            build_pod("default", "p1", None, PodPhase.PENDING,
                      {"cpu": 1000.0}, group_name="pg1")))
        # one scheduling cycle over the ingested state
        Scheduler(cache, conf=load_scheduler_conf(None)).run_once()
        bindings = _get(srv.port, "/v1/bindings")
        assert bindings == [{"pod": "default/p1", "node": "n1", "status": "BINDING"}]
        jobs = _get(srv.port, "/v1/jobs")
        assert jobs[0]["phase"] == "Running"
        queues = _get(srv.port, "/v1/queues")
        assert queues[0]["name"] == "default" and queues[0]["running"] == 1

    def test_pod_repost_is_upsert(self, server):
        cache, srv = server
        _post(srv.port, "/v1/queues", {"name": "default", "weight": 1})
        pod = serialize.pod_to_dict(
            build_pod("default", "p1", None, PodPhase.PENDING, {"cpu": 500.0}))
        _post(srv.port, "/v1/pods", pod)
        pod["requests"] = {"cpu": 700.0}
        _post(srv.port, "/v1/pods", pod)  # re-POST: update, not duplicate
        job = next(iter(cache.jobs.values()))
        assert len(job.tasks) == 1
        assert job.total_request.milli_cpu == 700.0

    def test_batched_ingest_list_body(self, server):
        """A list body applies the whole batch under one lock acquisition
        and ONE dirty-version advance — the high-QPS ingest path."""
        cache, srv = server
        _post(srv.port, "/v1/queues", {"name": "default", "weight": 1})
        v0 = cache.dirty.version
        pods = [
            serialize.pod_to_dict(build_pod(
                "default", f"bp{i}", None, PodPhase.PENDING, {"cpu": 100.0}))
            for i in range(6)
        ]
        resp = _post(srv.port, "/v1/pods", pods)
        assert resp == {"ok": True, "applied": 6}
        assert all(f"default/bp{i}" in cache.pods for i in range(6))
        assert cache.dirty.version == v0 + 1
        # batched DELETE takes the same path
        resp = _post_method(srv.port, "/v1/pods", pods[:2], "DELETE")
        assert resp == {"ok": True, "applied": 2}
        assert "default/bp0" not in cache.pods
        assert "default/bp2" in cache.pods

    def test_batched_ingest_rejects_malformed_batch_wholesale(self, server):
        cache, srv = server
        good = serialize.pod_to_dict(build_pod(
            "default", "gx", None, PodPhase.PENDING, {"cpu": 100.0}))
        with pytest.raises(urllib.error.HTTPError):
            _post(srv.port, "/v1/pods", [good, {"bogus_field": 1}])
        # the whole batch parses before any element applies
        assert "default/gx" not in cache.pods

    def test_delete_and_errors(self, server):
        cache, srv = server
        _post(srv.port, "/v1/queues", {"name": "q2", "weight": 3})
        assert "q2" in cache.queues
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/queues",
            data=json.dumps({"name": "q2"}).encode(), method="DELETE",
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=5)
        assert "q2" not in cache.queues
        with pytest.raises(urllib.error.HTTPError):
            _post(srv.port, "/v1/widgets", {})
        with pytest.raises(urllib.error.HTTPError):
            _post(srv.port, "/v1/pods", {"bogus_field": 1})


class TestQueueCLI:
    def test_create_and_list(self, capsys):
        cache = SchedulerCache()
        srv = AdminServer(cache, port=0)
        srv.start()
        try:
            server = f"http://127.0.0.1:{srv.port}"
            assert queue_cli.main(["--server", server, "create",
                                   "--name", "gold", "--weight", "5"]) == 0
            assert cache.queues["gold"].weight == 5
            assert queue_cli.main(["--server", server, "list"]) == 0
            out = capsys.readouterr().out
            assert "gold" in out and "Weight" in out
        finally:
            srv.stop()

    def test_master_mode_round_trip(self, capsys):
        """--master: create writes the Queue CRD to the cluster (the
        authoritative store, create.go:47-68), list reads CRDs back
        (list.go:51-87), and the scheduler ingests the created object
        through its normal translate path."""
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        store = {}

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                obj = _json.loads(self.rfile.read(n))
                store[obj["metadata"]["name"]] = obj
                body = _json.dumps(obj).encode()
                self.send_response(201)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                body = _json.dumps({"items": list(store.values())}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            master = f"http://127.0.0.1:{srv.server_address[1]}"
            # connection flags AFTER the subcommand (the documented form the
            # shared parent parser exists to support)
            assert queue_cli.main(["create", "--master", master,
                                   "--name", "gold", "--weight", "5"]) == 0
            stored = store["gold"]
            assert stored["apiVersion"] == "scheduling.incubator.k8s.io/v1alpha1"
            assert stored["kind"] == "Queue"
            assert stored["spec"]["weight"] == 5
            capsys.readouterr()  # drop create's output — list must stand alone
            assert queue_cli.main(["--master", master, "list"]) == 0
            out = capsys.readouterr().out
            row = [ln for ln in out.splitlines() if ln.startswith("gold")]
            assert row and "5" in row[0].split(), out
            # the object the CLI wrote is exactly what the scheduler's watch
            # ingests: apply it through the translate path
            from kube_batch_tpu.k8s.translate import apply_event

            cache = SchedulerCache()
            apply_event(cache, "queues", "ADDED", stored)
            assert cache.queues["gold"].weight == 5
        finally:
            srv.shutdown()


class TestRateLimiter:
    def test_bind_throttled_to_qps(self):
        from kube_batch_tpu.cache.fake import FakeBinder
        from kube_batch_tpu.cmd.server import RateLimitedBackend

        rl = RateLimitedBackend(FakeBinder(), qps=100.0, burst=5)
        pods = [build_pod("default", f"p{i}", None, PodPhase.PENDING, {})
                for i in range(15)]
        t0 = time.perf_counter()
        for p in pods:
            rl.bind(p, "n1")
        elapsed = time.perf_counter() - t0
        # 15 binds, burst 5 → ≥10 token waits at 100/s ≈ ≥0.1s
        assert elapsed >= 0.08
        assert len(rl._backend.binds) == 15

    def test_single_bucket_shared_across_seams(self):
        """Binder + evictor + status updater drain ONE token budget: the
        reference's writes all ride a single throttled rest.Config
        (server.go:69-70), so combined egress must not reach 3x qps."""
        from kube_batch_tpu.cache.fake import FakeBinder, FakeEvictor
        from kube_batch_tpu.cmd.server import (
            RateLimitedBackend, TokenBucket)

        bucket = TokenBucket(qps=100.0, burst=5)
        binder = RateLimitedBackend(FakeBinder(), bucket=bucket)
        evictor = RateLimitedBackend(FakeEvictor(), bucket=bucket)
        pods = [build_pod("default", f"p{i}", None, PodPhase.PENDING, {})
                for i in range(16)]
        t0 = time.perf_counter()
        for i, p in enumerate(pods):
            (binder.bind(p, "n1") if i % 2 == 0 else evictor.evict(p))
        elapsed = time.perf_counter() - t0
        # 16 writes against a SHARED burst of 5 → ≥11 waits at 100/s;
        # independent buckets would sail through both bursts in ~0.03s
        assert elapsed >= 0.08
        assert len(binder._backend.binds) == 8
        assert len(evictor._backend.evicts) == 8


class TestLeaderElection:
    def test_single_leader_and_failover(self, tmp_path):
        a = LeaderElector(str(tmp_path), identity="a",
                          lease_duration=0.4, renew_deadline=0.3, retry_period=0.05)
        b = LeaderElector(str(tmp_path), identity="b",
                          lease_duration=0.4, renew_deadline=0.3, retry_period=0.05)
        order = []

        def lead(elector, name, hold):
            def body():
                order.append(name)
                time.sleep(hold)
            elector.run(body)

        ta = threading.Thread(target=lead, args=(a, "a", 0.3), daemon=True)
        ta.start()
        time.sleep(0.1)
        assert a.is_leader() and not b.is_leader()
        tb = threading.Thread(target=lead, args=(b, "b", 0.1), daemon=True)
        tb.start()
        time.sleep(0.1)
        assert order == ["a"]  # b blocked while a's lease is valid
        ta.join(2)
        tb.join(2)
        assert order == ["a", "b"]  # release → standby takes over


class _LeaseStub:
    """In-memory coordination.k8s.io/v1 Lease apiserver with resourceVersion
    compare-and-swap — the contract K8sLeaseElector relies on (a stale PUT
    must 409, exactly like the real apiserver)."""

    def __init__(self):
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        store = self.store = {}
        lock = threading.Lock()
        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, obj=None):
                body = _json.dumps(obj or {}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _name(self):
                return self.path.rstrip("/").split("/")[-1]

            def _body(self):
                n = int(self.headers.get("Content-Length", 0))
                return _json.loads(self.rfile.read(n))

            def do_GET(self):
                with lock:
                    obj = store.get(self._name())
                self._send(200, obj) if obj else self._send(404)

            def do_POST(self):
                obj = self._body()
                name = (obj.get("metadata") or {}).get("name", "")
                with lock:
                    if name in store:
                        return self._send(409)
                    obj.setdefault("metadata", {})["resourceVersion"] = "1"
                    store[name] = obj
                    stub.writes += 1
                self._send(201, obj)

            def do_PUT(self):
                obj = self._body()
                name = self._name()
                with lock:
                    cur = store.get(name)
                    if cur is None:
                        return self._send(404)
                    if (obj.get("metadata") or {}).get("resourceVersion") != (
                        cur["metadata"]["resourceVersion"]
                    ):
                        return self._send(409)
                    obj["metadata"]["resourceVersion"] = str(
                        int(cur["metadata"]["resourceVersion"]) + 1
                    )
                    store[name] = obj
                    stub.writes += 1
                self._send(200, obj)

        self.writes = 0
        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.srv.serve_forever, daemon=True).start()
        self.url = f"http://127.0.0.1:{self.srv.server_address[1]}"

    def shutdown(self):
        self.srv.shutdown()


class TestK8sLeaseElection:
    def _elector(self, url, ident, **kw):
        from kube_batch_tpu.cmd.leader_election import K8sLeaseElector
        from kube_batch_tpu.k8s.transport import ApiTransport

        # whole seconds: the Lease wire format is leaseDurationSeconds
        kw.setdefault("lease_duration", 1.0)
        kw.setdefault("renew_deadline", 0.75)
        kw.setdefault("retry_period", 0.1)
        return K8sLeaseElector(
            ApiTransport(url), namespace="kube-system", identity=ident, **kw
        )

    def test_single_leader_and_failover(self):
        """Two electors on different 'hosts' (no shared filesystem — only
        the apiserver): one leads, the standby blocks while the lease is
        valid, release hands over (server.go:106-151 semantics)."""
        stub = _LeaseStub()
        try:
            a = self._elector(stub.url, "host-a")
            b = self._elector(stub.url, "host-b")
            order = []

            def lead(elector, name, hold):
                def body():
                    order.append(name)
                    time.sleep(hold)
                elector.run(body)

            ta = threading.Thread(target=lead, args=(a, "host-a", 0.6), daemon=True)
            ta.start()
            time.sleep(0.25)
            assert a.is_leader() and not b.is_leader()
            tb = threading.Thread(target=lead, args=(b, "host-b", 0.2), daemon=True)
            tb.start()
            time.sleep(0.2)
            assert order == ["host-a"]  # b blocked while a's lease is valid
            ta.join(4)
            tb.join(4)
            assert order == ["host-a", "host-b"]  # release → takeover
            # the release vacated the lease; b then took it and released
            spec = stub.store["kube-batch-tpu"]["spec"]
            assert spec["holderIdentity"] == ""
            assert spec["leaseTransitions"] >= 1
        finally:
            stub.shutdown()

    def test_sub_second_duration_rejected(self):
        import pytest as _pytest

        with _pytest.raises(ValueError):
            self._elector("http://x", "a", lease_duration=0.4)

    def test_expired_lease_takeover_and_cas(self):
        """A dead leader's expired lease is taken over; a stale
        resourceVersion write loses the CAS and reports failure, not a
        split brain."""
        stub = _LeaseStub()
        try:
            a = self._elector(stub.url, "host-a")
            b = self._elector(stub.url, "host-b")
            assert a._try_acquire_or_renew()          # a creates the lease
            assert not b._try_acquire_or_renew()      # valid → b fails
            time.sleep(1.1)                           # a dies; lease expires
            assert b._try_acquire_or_renew()          # b takes over
            assert stub.store["kube-batch-tpu"]["spec"]["holderIdentity"] == "host-b"
            assert stub.store["kube-batch-tpu"]["spec"]["leaseTransitions"] == 1
            # CAS: a PUT carrying a stale resourceVersion must 409 → False
            import urllib.request
            stale = dict(stub.store["kube-batch-tpu"])
            stale["metadata"] = dict(stale["metadata"], resourceVersion="0")
            req = urllib.request.Request(
                stub.url + "/apis/coordination.k8s.io/v1/namespaces/"
                "kube-system/leases/kube-batch-tpu",
                data=__import__("json").dumps(stale).encode(),
                headers={"Content-Type": "application/json"}, method="PUT",
            )
            try:
                urllib.request.urlopen(req)
                raise AssertionError("stale PUT must 409")
            except urllib.error.HTTPError as e:
                assert e.code == 409
        finally:
            stub.shutdown()

    def test_unreachable_apiserver_reports_failure(self):
        """Transport errors run the renew deadline down instead of raising
        out of the loop (the standby keeps retrying)."""
        e = self._elector("http://127.0.0.1:1", "host-x")  # nothing listens
        assert e._try_acquire_or_renew() is False
        assert e.is_leader() is False


class TestPersistence:
    def test_save_load_round_trip(self, tmp_path):
        """SURVEY.md §5.4: restart = reload durable state; the Inqueue phase
        survives (enqueue.go:115)."""
        from kube_batch_tpu.api.types import PodGroupPhase
        from kube_batch_tpu.cache.persistence import load_state, save_state

        cache = SchedulerCache()
        cache.add_queue(serialize.queue_from_dict({"name": "gold", "weight": 3}))
        cache.add_node(build_node("n1"))
        pg = PodGroup(name="pg1", min_member=2, queue="gold",
                      phase=PodGroupPhase.INQUEUE)
        cache.add_pod_group(pg)
        cache.add_pod(build_pod("default", "p1", "n1", PodPhase.RUNNING,
                                {"cpu": 500.0}, group_name="pg1"))
        path = str(tmp_path / "state.json")
        save_state(cache, path)

        fresh = SchedulerCache()
        assert load_state(fresh, path)
        assert fresh.queues["gold"].weight == 3
        assert fresh.jobs["default/pg1"].pod_group.phase == PodGroupPhase.INQUEUE
        # bound pod replays node accounting
        node = fresh.nodes["n1"]
        assert node.used.milli_cpu == 500.0
        assert not load_state(SchedulerCache(), str(tmp_path / "missing.json"))

    def test_shadow_pod_groups_not_persisted(self, tmp_path):
        from kube_batch_tpu.cache.persistence import load_state, save_state
        cache = SchedulerCache()
        cache.add_queue(serialize.queue_from_dict({"name": "default"}))
        cache.add_pod(build_pod("default", "solo", None, PodPhase.PENDING,
                                {"cpu": 100.0}))  # plain pod → shadow PG
        path = str(tmp_path / "state.json")
        save_state(cache, path)
        fresh = SchedulerCache()
        load_state(fresh, path)
        job = next(iter(fresh.jobs.values()))
        assert job.pod_group is not None and job.pod_group.shadow


class TestDebugEndpoints:
    def test_stacks(self):
        cache = SchedulerCache()
        srv = AdminServer(cache, port=0)
        srv.start()
        try:
            body = _get(srv.port, "/debug/stacks")
            assert "thread" in body
        finally:
            srv.stop()

    def test_pprof_samples_other_threads(self):
        """/debug/pprof is a SAMPLING profiler over every thread — it must
        attribute samples to a busy worker thread, not just itself."""
        import threading as _threading

        cache = SchedulerCache()
        srv = AdminServer(cache, port=0)
        srv.start()
        stop = _threading.Event()

        def busy():
            x = 0
            while not stop.is_set():
                x += 1

        t = _threading.Thread(target=busy, daemon=True)
        t.start()
        try:
            body = _get(srv.port, "/debug/pprof?seconds=0.5")
            assert "samples:" in body
            assert "busy" in body, body[:500]
        finally:
            stop.set()
            srv.stop()


class TestCacheSyncBarrier:
    def test_wait_for_cache_sync(self):
        """WaitForCacheSync analog (cache.go:363-384): the barrier blocks
        until signaled, and the bounded wait falls through on timeout."""
        cache = SchedulerCache()
        assert not cache.wait_for_cache_sync()        # not signaled yet
        assert not cache.wait_for_cache_sync(0.01)    # bounded wait times out
        cache.mark_synced()
        assert cache.wait_for_cache_sync()
        assert cache.wait_for_cache_sync(0.01)

    def test_sync_endpoint_signals_barrier(self):
        cache = SchedulerCache()
        srv = AdminServer(cache, port=0)
        srv.start()
        try:
            assert not cache.wait_for_cache_sync()
            _post(srv.port, "/v1/sync", {})
            assert cache.wait_for_cache_sync()
        finally:
            srv.stop()


class TestRestartWithBindings:
    def test_bound_pods_survive_restart_and_are_not_rescheduled(self, tmp_path):
        """Crash-restart story: binder acks persist pod.node_name, so a
        state-file round trip restores placements as Bound (Pending+nodeName
        → Bound, helpers.go:35-61) with correct node accounting, and the
        next cycle on the fresh process re-schedules nothing."""
        from kube_batch_tpu.api.types import TaskStatus
        from kube_batch_tpu.cache.persistence import load_state, save_state
        from kube_batch_tpu.framework.conf import load_scheduler_conf
        from kube_batch_tpu.scheduler import Scheduler

        cache = SchedulerCache()
        cache.add_queue(Queue(name="default", weight=1))
        cache.add_node(Node(name="n1", allocatable={
            "cpu": 8000.0, "memory": float(16 << 30), "pods": 110.0}))
        for i in range(3):
            cache.add_pod(Pod(name=f"p{i}", namespace="c1",
                              requests={"cpu": 1000.0,
                                        "memory": float(1 << 30)},
                              phase=PodPhase.PENDING))
        Scheduler(cache, conf=load_scheduler_conf(None)).run_once()
        assert len(cache.binder.binds) == 3
        path = str(tmp_path / "state.json")
        save_state(cache, path)

        fresh = SchedulerCache()
        assert load_state(fresh, path)
        # placements restored: tasks Bound on n1, idle reflects them
        for i in range(3):
            task = fresh.jobs[f"c1/p{i}"].tasks[f"c1/p{i}"]
            assert task.status == TaskStatus.BOUND
            assert task.node_name == "n1"
        assert fresh.nodes["n1"].used.milli_cpu == 3000
        # the restarted process schedules nothing new
        Scheduler(fresh, conf=load_scheduler_conf(None)).run_once()
        assert fresh.binder.binds == {}


class TestTokenBucketConcurrency:
    def test_take_sleeps_outside_the_lock(self):
        """ADVICE.md #3 regression: a waiter must reserve under the lock and
        sleep OUTSIDE it — a sleeper holding self._lock serializes the
        16-worker status pool and head-of-line blocks the bind loop."""
        from kube_batch_tpu.cmd.server import TokenBucket

        bucket = TokenBucket(qps=4.0, burst=1)
        bucket.take()  # consume the burst token; next take waits ~0.25s
        waiter = threading.Thread(target=bucket.take)
        waiter.start()
        try:
            time.sleep(0.05)  # let the waiter reserve and start sleeping
            acquired = bucket._lock.acquire(timeout=0.05)
            if acquired:
                bucket._lock.release()
            assert acquired, "take() held the lock through its sleep"
        finally:
            waiter.join()

    def test_parallel_waiters_keep_aggregate_rate(self):
        """Reservations are debt positions: N concurrent waiters sleep in
        parallel yet tokens still mint at qps overall."""
        from kube_batch_tpu.cmd.server import TokenBucket

        bucket = TokenBucket(qps=100.0, burst=1)
        threads = [threading.Thread(target=bucket.take) for _ in range(9)]
        t0 = time.perf_counter()
        bucket.take()  # burst token
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        # 10 takes, burst 1 → 9 minted tokens at 100/s ≈ ≥0.09s aggregate,
        # and nowhere near 9 serialized full waits either
        assert elapsed >= 0.07
        assert elapsed < 1.0
