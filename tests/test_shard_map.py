"""shard_map scale-out: bit-exactness vs the pjit oracle and the
single-device solve, zero steady-state retraces on both impls, the
authored-collective byte accounting, and the task-axis (2-D mesh) cycle.

The conftest forces an 8-device virtual CPU mesh; clusters here pad past
SHARD_MIN_NODES so the allocate action dispatches sharded.  KB_SHARD_MAP
toggles shard_map (default) vs the pjit oracle; KB_TASK_SHARDS=2 selects
the 2-D (tasks × nodes) mesh; KB_SHARD=0 forces the single-device path.
"""

from __future__ import annotations

import itertools
import os

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.framework.interface import get_action
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu.testing.synthetic import synthetic_cluster

N_NODES = 200   # pads to 256 == SHARD_MIN_NODES → the sharded path engages
N_TASKS = 240

_ENV_KEYS = ("KB_SHARD", "KB_SHARD_MAP", "KB_TASK_SHARDS", "KB_DEVICE_CACHE")


@pytest.fixture
def _env_guard():
    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _mk_cache(seed=0):
    return synthetic_cluster(
        n_tasks=N_TASKS, n_nodes=N_NODES, gang_size=4, n_queues=2, seed=seed
    )


def _churn(cache, rng, serial):
    """Seed-deterministic churn: complete one bound gang, add one gang."""
    from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod, PodGroup
    from kube_batch_tpu.api.types import PodPhase

    for uid, job in sorted(cache.jobs.items()):
        pods = [cache.pods.get(key) for key in sorted(job.tasks)]
        if pods and all(p is not None and p.node_name for p in pods):
            for p in pods:
                cache.delete_pod(p)
            cache.delete_pod_group(uid)
            break
    j = next(serial)
    cache.add_pod_group(PodGroup(
        name=f"sm{j}", namespace="shardmap", min_member=2,
        queue=f"q{j % 2}", creation_index=20_000 + j,
    ))
    for t in range(2):
        cache.add_pod(Pod(
            name=f"sm{j}-{t}", namespace="shardmap",
            requests={"cpu": float(rng.choice([250.0, 500.0])),
                      "memory": float(2 ** 30)},
            annotations={GROUP_NAME_ANNOTATION: f"sm{j}"},
            phase=PodPhase.PENDING,
            creation_index=(20_000 + j) * 10 + t,
        ))


def _run_cycles(cache, conf, cycles=4, seed=7):
    rng = np.random.default_rng(seed)
    serial = itertools.count(1)
    binds = []
    for _ in range(cycles):
        _churn(cache, rng, serial)
        ssn = open_session(cache, conf.tiers)
        try:
            for name in conf.actions:
                get_action(name).execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()
        binds.append(sorted(cache.binder.binds.items()))
    cols = cache.columns
    status = [
        (cols.task_by_row[r]._key, int(cols.t_status[r]))
        for r in np.flatnonzero(cols.t_valid).tolist()
    ]
    return binds, sorted(status)


def _session_snapshot(seed=3):
    cache = _mk_cache(seed)
    conf = load_scheduler_conf(None)
    ssn = open_session(cache, conf.tiers)
    try:
        from kube_batch_tpu.actions.allocate import (
            build_session_snapshot,
            session_allocate_config,
        )

        snap, _meta = build_session_snapshot(ssn)
        config = session_allocate_config(ssn)
    finally:
        close_session(ssn)
    return snap, config


# --------------------------------------------------------------------------
# cycle-level equivalence over randomized churn
# --------------------------------------------------------------------------


def test_cycles_shard_map_vs_pjit_vs_single(_env_guard):
    """Identical churn on three caches — shard_map (default), the pjit
    oracle (KB_SHARD_MAP=0), and the single-device solve (KB_SHARD=0) —
    must produce identical bind sequences and end state."""
    conf = load_scheduler_conf(None)
    for k in _ENV_KEYS:
        os.environ.pop(k, None)

    binds_sm, status_sm = _run_cycles(_mk_cache(), conf)
    assert get_action("allocate").last_solve_mode == "sharded"

    os.environ["KB_SHARD_MAP"] = "0"
    binds_pj, status_pj = _run_cycles(_mk_cache(), conf)
    os.environ.pop("KB_SHARD_MAP")

    os.environ["KB_SHARD"] = "0"
    binds_1, status_1 = _run_cycles(_mk_cache(), conf)
    os.environ.pop("KB_SHARD")

    assert binds_sm == binds_pj, "shard_map vs pjit binds diverged"
    assert status_sm == status_pj
    assert binds_sm == binds_1, "shard_map vs single-device binds diverged"
    assert status_sm == status_1


def test_cycles_task_axis_sharded(_env_guard):
    """A 2-D (tasks=2 × nodes=4) mesh cycle (KB_TASK_SHARDS=2) must match
    the single-device cycle bit-for-bit — the task-axis-sharded
    equivalence case."""
    conf = load_scheduler_conf(None)
    for k in _ENV_KEYS:
        os.environ.pop(k, None)

    os.environ["KB_TASK_SHARDS"] = "2"
    binds_2d, status_2d = _run_cycles(_mk_cache(), conf)
    assert get_action("allocate").last_solve_mode == "sharded"
    os.environ.pop("KB_TASK_SHARDS")

    os.environ["KB_SHARD"] = "0"
    binds_1, status_1 = _run_cycles(_mk_cache(), conf)

    assert binds_2d == binds_1, "task-axis-sharded binds diverged"
    assert status_2d == status_1


# --------------------------------------------------------------------------
# solve-level equivalence on a forced-4-device mesh (not the conftest 8)
# --------------------------------------------------------------------------


def test_forced_4_device_solves_bit_exact(_env_guard):
    import jax

    from kube_batch_tpu.ops.assignment import (
        allocate_solve,
        failure_histogram_solve,
    )
    from kube_batch_tpu.ops.eviction import EvictConfig, evict_solve
    from kube_batch_tpu.parallel.mesh import (
        allocate_solve_fn,
        evict_solve_fn,
        failure_histogram_fn,
        make_mesh,
    )

    snap, config = _session_snapshot()
    mesh = make_mesh(4)
    local = jax.device_get(allocate_solve(snap, config))
    with mesh:
        sm = jax.device_get(
            allocate_solve_fn(mesh, config, impl="shard_map")(snap))
        pj = jax.device_get(
            allocate_solve_fn(mesh, config, impl="pjit")(snap))
    for name in local._fields:
        assert np.array_equal(getattr(local, name), getattr(sm, name)), (
            f"shard_map {name} diverged on the 4-device mesh")
        assert np.array_equal(getattr(local, name), getattr(pj, name)), (
            f"pjit {name} diverged on the 4-device mesh")

    hist = jax.device_get(failure_histogram_solve(snap))
    with mesh:
        hist_sm = jax.device_get(
            failure_histogram_fn(mesh, impl="shard_map")(snap))
    assert np.array_equal(hist, hist_sm)

    for mode in ("reclaim", "preempt"):
        ec = EvictConfig(mode=mode, idle_gate=(mode == "reclaim"))
        ev = jax.device_get(evict_solve(snap, ec))
        with mesh:
            ev_sm = jax.device_get(
                evict_solve_fn(mesh, ec, impl="shard_map")(snap))
        for name in ev._fields:
            assert np.array_equal(getattr(ev, name), getattr(ev_sm, name)), (
                f"shard_map evict[{mode}] {name} diverged")


def test_enqueue_gate_mesh_matches_single():
    import jax

    from kube_batch_tpu.ops.admission import enqueue_gate_solve
    from kube_batch_tpu.parallel.mesh import enqueue_gate_solve_fn, make_mesh

    rng = np.random.default_rng(11)
    minr = rng.uniform(0, 4, (64, 3)).astype(np.float32)
    cand = rng.random(64) < 0.6
    idle0 = np.asarray([40.0, 30.0, 20.0], np.float32)
    quanta = np.full(3, 1e-3, np.float32)
    single = np.asarray(
        jax.device_get(enqueue_gate_solve(minr, cand, idle0, quanta)))
    mesh = make_mesh(8)
    with mesh:
        sharded = np.asarray(jax.device_get(
            enqueue_gate_solve_fn(mesh)(minr, cand, idle0, quanta)))
    assert np.array_equal(single, sharded)


# --------------------------------------------------------------------------
# zero steady-state retraces on both impls
# --------------------------------------------------------------------------


@pytest.mark.parametrize("impl_env", [{}, {"KB_SHARD_MAP": "0"}])
def test_zero_steady_state_retraces(_env_guard, impl_env):
    from kube_batch_tpu.utils import jitstats

    conf = load_scheduler_conf(None)
    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    os.environ.update(impl_env)
    cache = _mk_cache(seed=5)
    rng = np.random.default_rng(9)
    serial = itertools.count(1)

    def cycle():
        _churn(cache, rng, serial)
        ssn = open_session(cache, conf.tiers)
        try:
            for name in conf.actions:
                get_action(name).execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()

    for _ in range(3):   # warmup: compiles + scatter prewarm
        cycle()
    before = jitstats.total_compiles()
    for _ in range(3):   # steady state
        cycle()
    assert jitstats.total_compiles() == before, (
        f"steady-state retrace on impl={impl_env or 'shard_map'}")


# --------------------------------------------------------------------------
# authored-collective byte accounting
# --------------------------------------------------------------------------


def test_collective_bytes_scale_with_tasks_not_nodes():
    """The traced per-round collective bytes must be invariant to the node
    count and linear in the task count — the O(tasks) comms claim, checked
    against the compiled program's jaxpr."""
    from kube_batch_tpu.analysis.jaxpr_audit import abstract_snapshot
    from kube_batch_tpu.parallel.mesh import collective_stats, make_mesh

    mesh = make_mesh(8)
    base = collective_stats(mesh, snap=abstract_snapshot(T=256, N=512))
    nodes2 = collective_stats(mesh, snap=abstract_snapshot(T=256, N=1024))
    tasks2 = collective_stats(mesh, snap=abstract_snapshot(T=512, N=512))
    assert base["per_round_bytes"] > 0
    assert nodes2["per_round_bytes"] == base["per_round_bytes"], (
        "per-round collective bytes moved with the node count")
    assert tasks2["per_round_bytes"] == 2 * base["per_round_bytes"], (
        "per-round collective bytes are not linear in the task count")
    # the four dependent reductions (2×pmax, pmin, psum) are fused into
    # ONE stacked-payload all_gather per round — a single DCN latency hop
    round_ops = base["ops"]["per_round"]
    assert set(round_ops) == {"all_gather"}, round_ops
    assert round_ops["all_gather"]["count"] == 1, round_ops
    # the one-per-solve node-ledger gather grows with N, and only it
    assert nodes2["per_solve_bytes"] > base["per_solve_bytes"]
    # nested-loop accounting (KBT204's byte-formula inputs): the bidding
    # rounds are a dynamically-capped while INSIDE the outer gang-pass
    # while, so no static inner trip count exists — the expanded total
    # counts the site ×1 and the unbounded flag marks it as a floor
    assert base["per_round_bytes_expanded"] == base["per_round_bytes"]
    assert base["per_round_has_unbounded_inner_loop"] is True


def test_collective_bytes_task_axis_gathers():
    """On the 2-D mesh the per-round inventory gains the task-axis
    reassembly all_gathers; bytes stay O(tasks)."""
    from kube_batch_tpu.analysis.jaxpr_audit import abstract_snapshot
    from kube_batch_tpu.parallel.mesh import collective_stats, make_mesh

    mesh2 = make_mesh(8, task_shards=2)
    st = collective_stats(mesh2, snap=abstract_snapshot(T=256, N=512))
    assert "all_gather" in st["ops"]["per_round"]
    nodes2 = collective_stats(mesh2, snap=abstract_snapshot(T=256, N=1024))
    assert nodes2["per_round_bytes"] == st["per_round_bytes"]


# --------------------------------------------------------------------------
# adaptive per-shard scatter slot budgets
# --------------------------------------------------------------------------


def test_adaptive_ladder_shapes():
    from kube_batch_tpu.api.resident import (
        SHARD_SCATTER_SLOT_BUCKETS,
        adaptive_ladder,
    )

    # zero churn reproduces the static default exactly
    assert adaptive_ladder(0.0, 1024) == SHARD_SCATTER_SLOT_BUCKETS
    assert adaptive_ladder(5.0, 1024) == (16, 128, 1024)
    # sustained churn drops the too-small buckets
    assert adaptive_ladder(100.0, 1024) == (256, 1024)
    assert adaptive_ladder(600.0, 1024) == (1024,)
    # the hard cap clamps everything
    assert adaptive_ladder(0.0, 8) == (8,)


def test_ladder_retargets_without_steady_retrace(_env_guard):
    """A sustained churn burst retargets the ladder (prewarming the new
    buckets at the retarget), after which deltas of the new width scatter
    with ZERO fresh compiles — and values stay exact throughout."""
    from kube_batch_tpu.api import resident as res
    from kube_batch_tpu.parallel.mesh import make_mesh
    from kube_batch_tpu.utils import jitstats

    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    snap, _config = _session_snapshot(seed=8)
    c = res.ShardedPerCycleDeviceCache(make_mesh(8))
    c.swap(snap)
    assert c._ladder == res.SHARD_SCATTER_SLOT_BUCKETS
    host = np.asarray(snap.node_idle).copy()
    cur = snap
    # sustained 60-rows-in-one-shard churn: EWMA must climb past the
    # 16-bucket regime and retarget the base bucket upward
    for i in range(1, 14):
        host = host.copy()
        host[:60] += float(i)
        cur = cur._replace(node_idle=host)
        out = c.swap(cur)
        assert np.array_equal(host, np.asarray(out.node_idle))
    assert c.ladder_retargets > 0
    assert c._ladder[0] > 16
    assert c.counters()["slot_ladder"] == list(c._ladder)
    # post-retarget steady state: same-width deltas are jit cache hits
    before = jitstats.total_compiles()
    for i in range(3):
        host = host.copy()
        host[:60] -= 1.0
        cur = cur._replace(node_idle=host)
        out = c.swap(cur)
        assert np.array_equal(host, np.asarray(out.node_idle))
    assert jitstats.total_compiles() == before, (
        "retargeted ladder bucket was not pre-warmed")
