"""Native fast-path parity: every Resource op runs on both the C library and
the numpy fallback with identical results (native/resource_ops.c's contract)."""

from __future__ import annotations

import copy
import pickle

import numpy as np
import pytest

from kube_batch_tpu.api import resources as res_mod
from kube_batch_tpu.api.resources import DEFAULT_SPEC, ResourceSpec


@pytest.fixture(params=["native", "numpy"])
def lib_mode(request, monkeypatch):
    if request.param == "numpy":
        monkeypatch.setattr(res_mod, "_LIB", None)
    elif res_mod._LIB is None:
        pytest.skip("native library unavailable")
    return request.param


def _pair():
    a = DEFAULT_SPEC.build(32000, 1 << 34, 110, {"nvidia.com/gpu": 8000})
    b = DEFAULT_SPEC.build(1000, 1 << 30, 1, {"nvidia.com/gpu": 2000})
    return a, b


class TestParity:
    def test_add_sub_roundtrip(self, lib_mode):
        a, b = _pair()
        before = a.vec.copy()
        a.add_(b)
        assert a.milli_cpu == 33000
        a.sub_(b)
        np.testing.assert_allclose(a.vec, before)

    def test_sub_clamps_and_asserts(self, lib_mode):
        a, b = _pair()
        with pytest.raises(AssertionError):
            b.sub(a)  # underflow
        # clamp path with asserts off
        import os
        os.environ["PANIC_ON_ERROR"] = "false"
        try:
            c = b.sub(a)
            assert (c.vec >= 0).all()
        finally:
            del os.environ["PANIC_ON_ERROR"]

    def test_less_equal_tolerance(self, lib_mode):
        # excess below the quantum passes (resource_info.go:269-284)
        a = DEFAULT_SPEC.build(1005, 1 << 30, 1)
        b = DEFAULT_SPEC.build(1000, 1 << 30, 1)
        assert a.less_equal(b)       # 5m < 10m quantum
        assert not a.less_equal_strict(b)
        a2 = DEFAULT_SPEC.build(1020, 1 << 30, 1)
        assert not a2.less_equal(b)

    def test_set_max_and_share(self, lib_mode):
        a, b = _pair()
        b.set_max_(a)
        np.testing.assert_allclose(b.vec, a.vec)
        total = DEFAULT_SPEC.build(64000, 1 << 35, 220, {"nvidia.com/gpu": 16000})
        assert a.share(total) == pytest.approx(0.5)
        # pods dim excluded from share (semantic mask)
        tiny = DEFAULT_SPEC.build(0, 0, 220)
        assert tiny.share(total) == 0.0


class TestPointerLifetime:
    def test_vec_rebinding_refreshes_addr(self):
        a, b = _pair()
        a.vec = a.vec + b.vec  # the pre-native idiom must stay safe
        cpu = a.milli_cpu
        a.add_(b)
        assert a.milli_cpu == cpu + b.milli_cpu

    def test_deepcopy_and_pickle_get_fresh_buffers(self):
        a, _ = _pair()
        for other in (copy.deepcopy(a), pickle.loads(pickle.dumps(a))):
            other.add_(DEFAULT_SPEC.build(1000))
            assert other.milli_cpu == a.milli_cpu + 1000
            assert a.milli_cpu == 32000  # original untouched

    def test_spec_pickle_round_trip(self):
        spec = ResourceSpec(scalar_names=("x.com/npu",))
        back = pickle.loads(pickle.dumps(spec))
        assert back == spec
        r = back.build(100, scalars={"x.com/npu": 500})
        assert r.less_equal(back.build(200, scalars={"x.com/npu": 500}))


class TestGoLoopNative:
    def test_native_loop_matches_numpy_loop(self):
        """The C go-loop (native/go_pass.c) must reproduce the numpy
        re-creation's placements exactly — same control flow, same
        arithmetic — in both pass modes; otherwise its time is not a valid
        denominator for the speedup bracket."""
        import numpy as np
        import pytest

        from kube_batch_tpu.testing.go_baseline import (
            _workload,
            go_loop_allocate,
            go_loop_allocate_native,
        )

        (task_req, task_job, job_min, node_idle, node_alloc, quanta,
         nt, nn) = _workload(800, 64, 4, 3)
        base_assigned, base_stats = go_loop_allocate(
            task_req, task_job, job_min, node_idle.copy(), node_alloc, quanta
        )
        for pooled in (False, True):
            out = go_loop_allocate_native(
                task_req, task_job, job_min, node_idle.copy(), node_alloc,
                quanta, pooled=pooled, threads=4,
            )
            if out is None:
                pytest.skip("native go_pass library unavailable")
            assigned, stats = out
            np.testing.assert_array_equal(assigned, base_assigned)
            assert stats["placed"] == base_stats["placed"] > 0
