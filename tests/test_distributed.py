"""Two-process distributed smoke (VERDICT r4 weak #5): exercises
parallel/distributed.initialize(coordinator=...) with two real CPU
processes forming one 8-device cluster, and asserts the global-mesh solve
matches the single-process solve bit-for-bit on a small shape.

The production scale story this validates: node-axis sharding over a mesh
whose devices span processes (ICI within a host, DCN across), XLA/GSPMD
collectives inserted by the compiler (SURVEY.md §2.8/§5.8)."""

import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_global_mesh_solve_matches_single():
    from kube_batch_tpu.envutil import hardened_cpu_env

    coordinator = f"127.0.0.1:{_free_port()}"
    stripped = {
        k: v for k, v in os.environ.items()
        # each worker sets its own backend env; inherited JAX/XLA settings
        # (the conftest's 8-device flag) must not leak in
        if not k.startswith(("JAX_", "XLA_"))
    }
    # harden BEFORE the child interpreter starts: sitecustomize acts on the
    # env at startup, earlier than any code the worker itself runs
    env = hardened_cpu_env(n_devices=4, base=stripped)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    )
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coordinator, str(rank)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"distributed workers timed out; partial output: {outs}")
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert "MATCH placed=" in out, f"rank {rank} output:\n{out[-4000:]}"
        # the shard_map impl + per-host resident scatter round-trip ran too
        assert "RESIDENT OK" in out, f"rank {rank} output:\n{out[-4000:]}"


def test_initialize_reinit_guard_without_is_initialized(monkeypatch):
    """ADVICE.md #4 regression: on a jax version lacking
    jax.distributed.is_initialized, a second initialize() call must no-op
    via the module-level flag instead of raising from
    jax.distributed.initialize."""
    import jax

    from kube_batch_tpu.parallel import distributed

    calls = []

    class _Stub:
        # no is_initialized attribute at all — the old-jax shape
        @staticmethod
        def initialize(**kw):
            calls.append(kw)
            if len(calls) > 1:
                raise RuntimeError("coordinator already configured")

    monkeypatch.setattr(jax, "distributed", _Stub())
    monkeypatch.setattr(distributed, "_initialized", False)
    distributed.initialize(coordinator="h:1", num_processes=1, process_id=0)
    distributed.initialize(coordinator="h:1", num_processes=1, process_id=0)
    assert len(calls) == 1  # second call guarded by the fallback flag
