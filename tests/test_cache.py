"""SchedulerCache tests — the rebuild's cache/cache_test.go analog: feed
objects through the real handlers, assert on the cache's Jobs/Nodes content,
plus the resync repair path (cache.go:559-581) and snapshot filtering."""

from kube_batch_tpu.api.pod import Node, PodGroup, Queue
from kube_batch_tpu.api.types import PodPhase, TaskStatus

from tests.fixtures import GiB, build_cache, build_node, build_pod


class TestPodIngest:
    def test_add_pod_creates_shadow_job(self):
        """A plain owned pod creates a job keyed by its own name with a
        shadow PodGroup minMember=1 (event_handlers.go:42-67, util.go:42-60)."""
        cache = build_cache(queues=["default"])
        cache.add_pod(build_pod("ns", "p1", None, PodPhase.PENDING,
                                {"cpu": 1000, "memory": GiB}))
        assert "ns/p1" in cache.jobs
        job = cache.jobs["ns/p1"]
        assert job.pod_group.shadow and job.pod_group.min_member == 1
        assert len(job.tasks) == 1

    def test_add_bound_pod_accounts_on_node(self):
        cache = build_cache(queues=["default"], nodes=[build_node("n1", cpu=8000)])
        cache.add_pod(build_pod("ns", "p1", "n1", PodPhase.RUNNING,
                                {"cpu": 3000, "memory": GiB}))
        node = cache.nodes["n1"]
        assert node.idle.vec[0] == 5000
        assert node.used.vec[0] == 3000

    def test_pod_before_node_replays_accounting(self):
        """A bound pod arriving before its node is held on a nodeless
        NodeInfo; set_node replays the accounting (node_info.go OutOfSync)."""
        cache = build_cache(queues=["default"])
        cache.add_pod(build_pod("ns", "p1", "n1", PodPhase.RUNNING,
                                {"cpu": 3000, "memory": GiB}))
        assert not cache.nodes["n1"].ready
        cache.add_node(build_node("n1", cpu=8000))
        node = cache.nodes["n1"]
        assert node.ready
        assert node.idle.vec[0] == 5000

    def test_delete_pod_releases(self):
        cache = build_cache(queues=["default"], nodes=[build_node("n1", cpu=8000)])
        pod = build_pod("ns", "p1", "n1", PodPhase.RUNNING,
                        {"cpu": 3000, "memory": GiB})
        cache.add_pod(pod)
        cache.delete_pod(pod)
        assert cache.nodes["n1"].idle.vec[0] == 8000
        assert "ns/p1" not in cache.jobs  # shadow job collected

    def test_update_pod_moves_status(self):
        cache = build_cache(queues=["default"], nodes=[build_node("n1")])
        pod = build_pod("ns", "p1", None, PodPhase.PENDING,
                        {"cpu": 1000, "memory": GiB})
        cache.add_pod(pod)
        import dataclasses
        bound = dataclasses.replace(pod, node_name="n1", phase=PodPhase.RUNNING)
        cache.update_pod(bound)
        job = cache.jobs["ns/p1"]
        task = next(iter(job.tasks.values()))
        assert task.status == TaskStatus.RUNNING
        assert task.node_name == "n1"

    def test_foreign_scheduler_unbound_pod_ignored(self):
        """Informer filter (cache.go:283-305): unbound pods of another
        scheduler are not ours; bound ones still count for node usage."""
        cache = build_cache(queues=["default"], nodes=[build_node("n1", cpu=8000)])
        cache.add_pod(build_pod("ns", "other", None, PodPhase.PENDING,
                                {"cpu": 1000, "memory": GiB},
                                scheduler_name="default-scheduler"))
        assert cache.jobs == {}
        cache.add_pod(build_pod("ns", "bound", "n1", PodPhase.RUNNING,
                                {"cpu": 1000, "memory": GiB},
                                scheduler_name="default-scheduler"))
        assert cache.nodes["n1"].used.vec[0] == 1000


class TestPodGroupQueueIngest:
    def test_podgroup_defaults_queue(self):
        cache = build_cache(queues=["default"])
        cache.add_pod_group(PodGroup(name="pg", namespace="ns", min_member=2))
        assert cache.jobs["ns/pg"].queue == "default"

    def test_delete_podgroup_keeps_tasked_job(self):
        cache = build_cache(queues=["default"])
        cache.add_pod(build_pod("ns", "p1", None, PodPhase.PENDING,
                                {"cpu": 1000, "memory": GiB}, group_name="pg"))
        cache.add_pod_group(PodGroup(name="pg", namespace="ns", min_member=1))
        cache.delete_pod_group("ns/pg")
        assert "ns/pg" in cache.jobs  # still has a task
        assert cache.jobs["ns/pg"].pod_group is None

    def test_queue_crud(self):
        cache = build_cache()
        cache.add_queue(Queue(name="q1", weight=4))
        assert cache.queues["q1"].weight == 4
        cache.delete_queue("q1")
        assert "q1" not in cache.queues


class TestSnapshotFilters:
    def test_job_without_podgroup_excluded(self):
        """Snapshot excludes jobs with no PodGroup (cache.go:625-633) — can't
        happen through add_pod (shadow groups), so build directly."""
        cache = build_cache(queues=["default"])
        from kube_batch_tpu.api.job_info import JobInfo
        cache.jobs["ns/bare"] = JobInfo("ns/bare", cache.spec)
        snap = cache.snapshot()
        assert "ns/bare" not in snap.jobs

    def test_job_with_unknown_queue_excluded(self):
        cache = build_cache(queues=["default"])
        cache.add_pod_group(PodGroup(name="pg", namespace="ns", queue="ghost"))
        snap = cache.snapshot()
        assert snap.jobs == {}

    def test_not_ready_node_excluded(self):
        cache = build_cache(queues=["default"],
                            nodes=[build_node("up"), build_node("down", ready=False)])
        snap = cache.snapshot()
        assert set(snap.nodes) == {"up"}

    def test_snapshot_is_a_deep_clone(self):
        cache = build_cache(queues=["default"], nodes=[build_node("n1", cpu=8000)])
        cache.add_pod(build_pod("ns", "p1", None, PodPhase.PENDING,
                                {"cpu": 1000, "memory": GiB}))
        snap = cache.snapshot()
        snap.nodes["n1"].idle.vec[0] = 0
        next(iter(snap.jobs.values())).min_available = 99
        assert cache.nodes["n1"].idle.vec[0] == 8000
        assert cache.jobs["ns/p1"].min_available == 1


class TestResyncRepair:
    def test_failed_bind_resyncs_task(self):
        """A binder failure queues the task; process_resync_tasks restores it
        to the pre-bind state from the pod store (cache.go:478-484,559-581)."""
        class ExplodingBinder:
            def bind(self, pod, hostname):
                raise RuntimeError("apiserver down")

        cache = build_cache(queues=["default"], nodes=[build_node("n1")])
        cache.binder = ExplodingBinder()
        pod = build_pod("ns", "p1", None, PodPhase.PENDING,
                        {"cpu": 1000, "memory": GiB})
        cache.add_pod(pod)
        task = next(iter(cache.jobs["ns/p1"].tasks.values()))
        cache.bind(task, "n1")
        assert len(cache.err_tasks) == 1
        cache.process_resync_tasks()
        assert cache.err_tasks == []
        task = next(iter(cache.jobs["ns/p1"].tasks.values()))
        assert task.status == TaskStatus.PENDING
        assert task.node_name is None


class TestResyncBackoffAndQuarantine:
    """The bounded repair queue (cache/resync.py): per-task exponential
    backoff in repair ticks, poison quarantine with a condition, breaker
    parks exempt from the poison budget, release on external change."""

    def _failing_cache(self):
        class ExplodingBinder:
            def bind(self, pod, hostname):
                raise RuntimeError("apiserver down")

        cache = build_cache(queues=["default"], nodes=[build_node("n1")])
        cache.binder = ExplodingBinder()
        pod = build_pod("ns", "p1", None, PodPhase.PENDING,
                        {"cpu": 1000, "memory": GiB})
        cache.add_pod(pod)
        return cache

    def _task(self, cache):
        return next(iter(cache.jobs["ns/p1"].tasks.values()))

    def test_repeat_failures_escalate_backoff(self):
        cache = self._failing_cache()
        cache.bind(self._task(cache), "n1")      # attempt 1 parks
        cache.process_resync_tasks()             # tick 1: due (delay 1)
        assert cache.err_tasks == []
        cache.bind(self._task(cache), "n1")      # attempt 2 parks: delay 2
        cache.process_resync_tasks()             # tick 2: NOT yet due
        assert len(cache.err_tasks) == 1
        cache.process_resync_tasks()             # tick 3: due now
        assert cache.err_tasks == []

    def test_poison_task_quarantined_with_condition(self):
        cache = self._failing_cache()
        cache.resync.poison_after = 3
        cache.resync.backoff_cap = 1             # keep the test short
        for _ in range(3):
            cache.bind(self._task(cache), "n1")
            cache.process_resync_tasks()
        # the 3rd real failure exhausted the budget: one more pass shelves
        cache.process_resync_tasks()
        assert "ns/p1" in cache.resync.quarantined
        assert cache.err_tasks == []             # out of the retry flow
        cond = cache.pod_conditions["ns/p1"]
        assert cond["status"] == "False" and "quarantined" in cond["message"]
        # parked again (a stray late failure) → still shelved, not retried
        cache.resync_task(self._task(cache))
        cache.process_resync_tasks()
        assert "ns/p1" in cache.resync.quarantined

    def test_external_pod_update_releases_quarantine(self):
        import dataclasses

        cache = self._failing_cache()
        cache.resync.poison_after = 1
        cache.bind(self._task(cache), "n1")
        cache.process_resync_tasks()
        assert "ns/p1" in cache.resync.quarantined
        # the user edits the pod: quarantine releases, history resets
        cache.update_pod(dataclasses.replace(cache.pods["ns/p1"]))
        assert "ns/p1" not in cache.resync.quarantined
        assert cache.resync.released_total == 1

    def test_pod_deletion_forgets_all_bookkeeping(self):
        cache = self._failing_cache()
        cache.bind(self._task(cache), "n1")
        assert len(cache.err_tasks) == 1
        cache.delete_pod(cache.pods["ns/p1"])
        assert cache.err_tasks == []
        cache.process_resync_tasks()             # nothing resurrects

    def test_breaker_parks_never_poison(self):
        cache = self._failing_cache()
        cache.resync.poison_after = 2
        task = self._task(cache)
        for _ in range(10):
            cache.resync_task(task, reason="breaker-open")
            cache.process_resync_tasks()
        for _ in range(cache.resync.backoff_cap + 1):
            cache.process_resync_tasks()         # drain the parked entry
        assert cache.resync.quarantined == {}
        assert cache.resync.parked_by_reason["breaker-open"] == 10

    def test_overflow_forces_oldest_due_instead_of_dropping(self):
        from kube_batch_tpu.cache.resync import ResyncQueue

        class T:
            def __init__(self, k):
                self._k = k

            def key(self):
                return self._k

        q = ResyncQueue(backoff_cap=64, poison_after=99, max_entries=4)
        for i in range(8):
            t = T(f"t{i}")
            q.park(t)
            q.park(t)  # second park → due far in the future
        assert len(q) == 8
        due, poisoned = q.tick()
        assert poisoned == []
        assert len(due) == 4  # the bound forced the oldest backlog due
        assert len(q) == 4


class TestDegradedStatusShedding:
    def test_shed_flag_skips_serial_status_writes(self):
        writes = []

        class Updater:
            def update_pod_group(self, pg):
                writes.append(pg)

        cache = build_cache(queues=["default"])
        cache.status_updater = Updater()
        cache.add_pod_group(PodGroup(name="pg", namespace="ns",
                                     queue="default"))
        job = cache.jobs["ns/pg"]
        cache.shed_status_writes = True
        cache.update_job_statuses_bulk([(job, True, False)])
        assert writes == []              # shed (non-parallel-safe → skip)
        cache.shed_status_writes = False
        cache._status_next_write.clear()
        cache.update_job_statuses_bulk([(job, True, False)])
        assert len(writes) == 1          # healthy cycle writes again

    def test_updater_degraded_probe_sheds_queue_status(self):
        wrote = []

        class Updater:
            degraded_now = True

            def update_pod_group(self, pg):
                pass

            def update_queue_status(self, name, counts):
                wrote.append(name)

            def degraded(self):
                return self.degraded_now

        cache = build_cache(queues=["default"])
        cache.status_updater = Updater()
        from kube_batch_tpu.api.types import queue_phase_counts

        counts = {"default": queue_phase_counts()}
        counts["default"]["pending"] = 1
        cache.update_queue_statuses(counts)
        assert wrote == []               # breaker open → shed
        Updater.degraded_now = False
        cache.update_queue_statuses(counts)
        assert wrote == ["default"]      # healthy close converges


class TestStatusRateLimit:
    def test_condition_only_updates_rate_limited(self):
        """job_updater.go:20-31: condition-only PodGroup writes throttle to
        one per minute; phase changes always write."""
        from kube_batch_tpu.api.types import PodGroupPhase
        from kube_batch_tpu.cache.cache import SchedulerCache
        cache = SchedulerCache()
        cache.add_queue(Queue(name="default"))
        cache.add_pod_group(PodGroup(name="pg", namespace="ns", min_member=1))
        job = cache.jobs["ns/pg"].clone()
        job.pod_group.phase = PodGroupPhase.PENDING
        cache.update_job_status(job)
        n0 = len(cache.status_updater.pod_groups)
        # same phase, new condition → rate-limited, no write
        from kube_batch_tpu.api.pod import PodGroupCondition
        job.pod_group.conditions.append(PodGroupCondition(type="Unschedulable"))
        cache.update_job_status(job)
        assert len(cache.status_updater.pod_groups) == n0
        # phase change → writes through immediately
        job.pod_group.phase = PodGroupPhase.RUNNING
        cache.update_job_status(job)
        assert len(cache.status_updater.pod_groups) == n0 + 1


class TestBulkBindPresums:
    def test_mid_cycle_resreq_update_invalidates_presum(self):
        """A pod whose resources were updated between snapshot and commit
        must be accounted at its NEW resreq — the session's presummed vector
        is stale and bulk_bind has to fall back to accumulation (detected by
        resreq object identity; TaskInfo.clone shares the Resource)."""
        import dataclasses

        import numpy as np

        cache = build_cache(queues=["default"], nodes=[build_node("n1", cpu=8000)])
        pod = build_pod("ns", "p1", None, PodPhase.PENDING,
                        {"cpu": 1000, "memory": GiB})
        cache.add_pod(pod)
        snap = cache.snapshot()
        session_task = next(iter(snap.jobs["ns/p1"].tasks.values()))
        # mid-cycle ingest: requests grow to 2000m (replaces the TaskInfo)
        cache.update_pod(dataclasses.replace(pod, requests={"cpu": 2000.0,
                                                            "memory": GiB}))
        # session-side presums still say 1000m
        stale_vec = session_task.resreq.vec.copy()
        cache.bulk_bind(
            [(session_task, "n1")],
            job_sums={"ns/p1": (1, stale_vec)},
            node_sums={"n1": (1, stale_vec)},
        )
        cache.flush_binds()
        job = cache.jobs["ns/p1"]
        assert job.allocated.milli_cpu == 2000  # new resreq, not the presum
        node = cache.nodes["n1"]
        assert node.used.milli_cpu == 2000
        assert node.idle.milli_cpu == 6000


class TestExclusiveSessionSafety:
    def test_deferred_update_does_not_clobber_binding(self):
        """A client pod update deferred past the cycle's bind (exclusive
        session gate) must not erase the placement: nodeName is write-once,
        scheduler-owned, and binder acks persist it on the stored pod."""
        import dataclasses

        from kube_batch_tpu import actions as _a  # noqa: F401
        from kube_batch_tpu import plugins as _p  # noqa: F401
        from kube_batch_tpu.framework.conf import load_scheduler_conf
        from kube_batch_tpu.framework.interface import get_action
        from kube_batch_tpu.framework.session import close_session, open_session

        cache = build_cache(queues=["default"], nodes=[build_node("n1")])
        pod = build_pod("ns", "p1", None, PodPhase.PENDING,
                        {"cpu": 1000, "memory": GiB})
        cache.add_pod(pod)
        conf = load_scheduler_conf(None)
        ssn = open_session(cache, conf.tiers)
        # informer delivers an annotation-only update mid-cycle — deferred
        cache.update_pod(dataclasses.replace(
            pod, annotations={"touched": "yes"}))
        get_action("allocate").execute(ssn)
        close_session(ssn)  # flushes binder acks, then applies the update
        assert cache.binder.binds == {"ns/p1": "n1"}
        # the rebuilt task carries the binding (pod.node_name was acked)
        task = cache.jobs["ns/p1"].tasks["ns/p1"]
        assert task.node_name == "n1"
        assert task.status == TaskStatus.BOUND
        assert cache.nodes["n1"].used.milli_cpu == 1000
        # a second cycle must not double-place it
        ssn2 = open_session(cache, conf.tiers)
        get_action("allocate").execute(ssn2)
        close_session(ssn2)
        assert len(cache.binder.channel) == 1  # exactly one bind ever

    def test_crashed_cycle_recovers_via_pod_store_rebuild(self):
        """A cycle that dies mid-mutation in exclusive mode must not leak
        phantom allocations: run_forever rebuilds from the pod store and the
        next cycle places everything."""
        import threading
        import time as _time

        from kube_batch_tpu import actions as _a  # noqa: F401
        from kube_batch_tpu import plugins as _p  # noqa: F401
        from kube_batch_tpu.framework.interface import Action, register_action
        from kube_batch_tpu.framework.conf import parse_scheduler_conf
        from kube_batch_tpu.scheduler import Scheduler

        boom = [2]  # explode on the first two cycles, after allocate ran

        class ExplodingAction(Action):
            name = "explode"

            def execute(self, ssn):
                if boom[0] > 0:
                    boom[0] -= 1
                    raise RuntimeError("mid-cycle crash")

        register_action(ExplodingAction())
        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1")],
            pods=[build_pod("c1", f"p{i}", None, PodPhase.PENDING,
                            {"cpu": 1000, "memory": GiB}) for i in range(3)],
        )
        conf = parse_scheduler_conf(
            'actions: "allocate, explode"\n'
            "tiers:\n- plugins:\n  - name: gang\n  - name: drf\n"
        )
        sched = Scheduler(cache, conf=conf, schedule_period=0.05)
        t = threading.Thread(target=sched.run_forever, daemon=True)
        t.start()
        try:
            deadline = _time.monotonic() + 15
            while _time.monotonic() < deadline and len(cache.binder.binds) < 3:
                _time.sleep(0.05)
        finally:
            sched.stop()
            t.join(5)
        assert len(cache.binder.binds) == 3
        # no phantom allocations: node accounting equals the placed pods
        assert cache.nodes["n1"].used.milli_cpu == 3000
        assert cache.nodes["n1"].idle.milli_cpu == \
            cache.nodes["n1"].allocatable.milli_cpu - 3000

    def test_deleted_priority_class_stops_conferring(self):
        """Priority resolution is recomputed per session (cache.go:610-620):
        deleting a PriorityClass resets its jobs to the default."""
        from kube_batch_tpu.api.pod import PriorityClass

        cache = build_cache(queues=["default"], nodes=[build_node("n1")])
        cache.add_priority_class(PriorityClass(name="high", value=100))
        cache.add_pod_group(PodGroup(name="pg", namespace="ns", min_member=1,
                                     queue="default", priority_class="high"))
        view = cache.session_view()
        assert view.jobs["ns/pg"].priority == 100
        cache.delete_priority_class("high")
        view = cache.session_view()
        assert view.jobs["ns/pg"].priority == 0
