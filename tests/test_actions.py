"""Fake-backend session tests of the actions — the rebuild's analog of
actions/allocate/allocate_test.go, preempt_test.go, reclaim_test.go: real
cache + real handlers + fake binder/evictor, assert on captured effects."""

import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.pod import PodGroup, Queue
from kube_batch_tpu.api.types import PodGroupPhase, PodPhase, TaskStatus
from kube_batch_tpu.framework.conf import parse_scheduler_conf
from kube_batch_tpu.framework.interface import get_action
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu.scheduler import Scheduler

from tests.fixtures import GiB, build_cache, build_node, build_pod

TWO_TIER_CONF = """
actions: "allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def run_actions(cache, conf_text=TWO_TIER_CONF, action_names=None):
    conf = parse_scheduler_conf(conf_text)
    ssn = open_session(cache, conf.tiers)
    for name in action_names or conf.actions:
        get_action(name).execute(ssn)
    close_session(ssn)
    cache.flush_binds()  # binder dispatch is async (cache.go:478)
    return ssn


class TestAllocateAction:
    def test_gang_job_binds_all_tasks(self):
        """allocate_test.go "allocate for gang": minMember gang placed and
        bound through the FakeBinder."""
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg1", namespace="c1", min_member=3, queue="default")],
            nodes=[build_node("n1", cpu=4000, mem=8 * GiB), build_node("n2", cpu=4000, mem=8 * GiB)],
            pods=[
                build_pod("c1", f"p{i}", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="pg1")
                for i in range(3)
            ],
        )
        run_actions(cache)
        assert len(cache.binder.binds) == 3
        assert set(cache.binder.binds) == {"c1/p0", "c1/p1", "c1/p2"}
        assert all(n in ("n1", "n2") for n in cache.binder.binds.values())

    def test_unsatisfiable_gang_binds_nothing(self):
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg1", namespace="c1", min_member=5, queue="default")],
            nodes=[build_node("n1", cpu=2000, mem=8 * GiB)],
            pods=[
                build_pod("c1", f"p{i}", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="pg1")
                for i in range(5)
            ],
        )
        run_actions(cache)
        assert cache.binder.binds == {}
        # job marked unschedulable at close (gang.go:132-175)
        job = cache.jobs["c1/pg1"]
        assert any(c.type == "Unschedulable" for c in job.pod_group.conditions)

    def test_plain_pod_shadow_podgroup(self):
        """A plain pod (no group annotation) gets a shadow PodGroup
        (cache/util.go:42-60) and schedules alone."""
        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1")],
            pods=[build_pod("c1", "solo", None, PodPhase.PENDING, {"cpu": 500, "memory": GiB})],
        )
        run_actions(cache)
        assert cache.binder.binds == {"c1/solo": "n1"}

    def test_respects_existing_usage(self):
        """Running pods already on the node shrink idle."""
        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1", cpu=4000, mem=8 * GiB)],
            pods=[
                build_pod("c1", "existing", "n1", PodPhase.RUNNING, {"cpu": 3000, "memory": GiB}),
                build_pod("c1", "new1", None, PodPhase.PENDING, {"cpu": 2000, "memory": GiB}),
                build_pod("c1", "new2", None, PodPhase.PENDING, {"cpu": 1000, "memory": GiB}),
            ],
        )
        run_actions(cache)
        # only the 1000m pod fits next to the 3000m resident
        assert cache.binder.binds == {"c1/new2": "n1"}

    def test_node_selector_respected(self):
        cache = build_cache(
            queues=["default"],
            nodes=[
                build_node("gpu-node", labels={"accel": "gpu"}),
                build_node("cpu-node", labels={}),
            ],
            pods=[
                build_pod("c1", "wants-gpu", None, PodPhase.PENDING,
                          {"cpu": 500, "memory": GiB}, node_selector={"accel": "gpu"}),
            ],
        )
        run_actions(cache)
        assert cache.binder.binds == {"c1/wants-gpu": "gpu-node"}

    def test_pending_phase_podgroup_skipped_without_enqueue(self):
        """allocate.go:50-52: explicit Pending phase gates allocation."""
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg1", namespace="c1", min_member=1, queue="default",
                                 phase=PodGroupPhase.PENDING)],
            nodes=[build_node("n1")],
            pods=[build_pod("c1", "p0", None, PodPhase.PENDING,
                            {"cpu": 1000, "memory": GiB}, group_name="pg1")],
        )
        run_actions(cache, action_names=["allocate"])
        assert cache.binder.binds == {}

    def test_enqueue_promotes_then_allocates(self):
        """enqueue.go:102-117 → Inqueue → allocate binds."""
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg1", namespace="c1", min_member=1, queue="default",
                                 phase=PodGroupPhase.PENDING)],
            nodes=[build_node("n1")],
            pods=[build_pod("c1", "p0", None, PodPhase.PENDING,
                            {"cpu": 1000, "memory": GiB}, group_name="pg1")],
        )
        run_actions(cache, action_names=["enqueue", "allocate"])
        assert cache.binder.binds == {"c1/p0": "n1"}
        assert cache.jobs["c1/pg1"].pod_group.phase == PodGroupPhase.RUNNING


class TestBackfillAction:
    def test_best_effort_backfilled(self):
        """backfill.go:55-89: BestEffort pods placed without scoring."""
        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1", cpu=100, mem=GiB)],  # nearly no capacity
            pods=[build_pod("c1", "be", None, PodPhase.PENDING, {})],
        )
        run_actions(cache)
        assert cache.binder.binds == {"c1/be": "n1"}

    def test_backfill_into_unready_gang_reverts_at_close(self):
        """ADVICE r2 (high): Session.allocate leaves a task ALLOCATED when
        its job never turns ready in the cycle; the exclusive (no-clone)
        close must revert it to PENDING on the authoritative cache — not
        leak node accounting and phantom gang readiness across cycles."""
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg1", namespace="c1", min_member=2, queue="default")],
            nodes=[build_node("n1", cpu=100, mem=GiB)],
            pods=[
                # BestEffort member — backfill places it via Session.allocate
                build_pod("c1", "be", None, PodPhase.PENDING, {}, group_name="pg1"),
                # sibling that can never fit → job never ready (1 < 2)
                build_pod("c1", "big", None, PodPhase.PENDING,
                          {"cpu": 64000, "memory": 64 * GiB}, group_name="pg1"),
            ],
        )
        run_actions(cache)
        assert cache.binder.binds == {}
        job = cache.jobs["c1/pg1"]
        task = job.tasks["c1/be"]
        assert task.status == TaskStatus.PENDING
        assert task.node_name is None
        assert job.ready_task_num == 0
        # the node must be back to pristine accounting — no resident tasks
        # at all (used.is_empty() alone is vacuous for a BestEffort resreq)
        assert not cache.nodes["n1"].tasks
        assert cache.nodes["n1"].used.is_empty()


class TestPreemptAction:
    def test_high_priority_job_preempts_within_queue(self):
        """preempt_test.go: a starved high-priority gang evicts a running
        lower-priority job's tasks in the same queue."""
        cache = build_cache(
            queues=["default"],
            pod_groups=[
                PodGroup(name="low", namespace="c1", min_member=1, queue="default"),
                PodGroup(name="high", namespace="c1", min_member=1, queue="default",
                         priority_class="high-prio"),
            ],
            nodes=[build_node("n1", cpu=2000, mem=4 * GiB, pods=10)],
            pods=[
                build_pod("c1", "low-1", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
                build_pod("c1", "low-2", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
                build_pod("c1", "high-1", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="high", priority=100),
            ],
        )
        from kube_batch_tpu.api.pod import PriorityClass

        cache.add_priority_class(PriorityClass(name="high-prio", value=100))
        run_actions(cache, action_names=["preempt"])
        assert len(cache.evictor.evicts) == 1
        assert cache.evictor.evicts[0].startswith("c1/low-")

    def test_no_preemption_when_gang_would_break(self):
        """gang.go:71-94: can't evict below the victim job's minAvailable."""
        cache = build_cache(
            queues=["default"],
            pod_groups=[
                PodGroup(name="low", namespace="c1", min_member=2, queue="default"),
                PodGroup(name="high", namespace="c1", min_member=1, queue="default"),
            ],
            nodes=[build_node("n1", cpu=2000, mem=4 * GiB, pods=10)],
            pods=[
                build_pod("c1", "low-1", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
                build_pod("c1", "low-2", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
                build_pod("c1", "high-1", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="high", priority=100),
            ],
        )
        run_actions(cache, action_names=["preempt"])
        assert cache.evictor.evicts == []


class TestReclaimAction:
    def test_starved_queue_reclaims_from_overfed_queue(self):
        """reclaim_test.go / queue.go e2e: queue B's pending task evicts
        queue A's running task when A is over its deserved share."""
        cache = build_cache(
            queues=[Queue(name="qa", weight=1), Queue(name="qb", weight=1)],
            pod_groups=[
                PodGroup(name="ja", namespace="c1", min_member=1, queue="qa"),
                PodGroup(name="jb", namespace="c1", min_member=1, queue="qb"),
            ],
            nodes=[build_node("n1", cpu=2000, mem=4 * GiB, pods=10)],
            pods=[
                build_pod("c1", "a-1", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="ja"),
                build_pod("c1", "a-2", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="ja"),
                build_pod("c1", "b-1", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="jb"),
            ],
        )
        run_actions(cache, action_names=["reclaim"])
        assert len(cache.evictor.evicts) == 1
        assert cache.evictor.evicts[0].startswith("c1/a-")


class TestNodesFitDelta:
    def test_pipeline_on_releasing_records_fit_delta(self):
        """allocate.go:170-175: a task that fits a node's Releasing but not
        its Idle is Pipelined AND leaves a NodesFitDelta shortfall diagnostic
        on its (session) job."""
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg1", namespace="c1", min_member=1,
                                 queue="default")],
            nodes=[build_node("n1", cpu=4000, mem=8 * GiB),
                   build_node("n2", cpu=1000, mem=8 * GiB)],
            pods=[
                # running pod being deleted → RELEASING: holds all of n1 idle
                build_pod("c1", "dying", "n1", PodPhase.RUNNING,
                          {"cpu": 4000, "memory": GiB}, deleting=True),
                # pg1 is already Ready via this running member, so the
                # pipelined placement below commits (job.Ready ≥ minMember)
                build_pod("c1", "r0", "n2", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="pg1"),
                build_pod("c1", "newb", None, PodPhase.PENDING,
                          {"cpu": 3000, "memory": GiB}, group_name="pg1"),
            ],
        )
        conf = parse_scheduler_conf(TWO_TIER_CONF)
        ssn = open_session(cache, conf.tiers)
        get_action("allocate").execute(ssn)
        job = ssn.jobs["c1/pg1"]
        task = job.tasks["c1/newb"]
        assert task.status == TaskStatus.PIPELINED
        assert task.node_name == "n1"
        delta = job.nodes_fit_delta.get("n1")
        close_session(ssn)
        assert delta is not None
        # idle cpu was 0, request 3000 → shortfall ≥ 3000
        assert delta.milli_cpu >= 3000


class TestSchedulerLoop:
    def test_run_once_end_to_end(self):
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg1", namespace="c1", min_member=2, queue="default")],
            nodes=[build_node("n1")],
            pods=[
                build_pod("c1", f"p{i}", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="pg1")
                for i in range(2)
            ],
        )
        sched = Scheduler(cache)
        sched.run_once()
        assert len(cache.binder.binds) == 2

    def test_unknown_action_raises(self):
        cache = build_cache(queues=["default"])
        conf = parse_scheduler_conf('actions: "bogus"\ntiers: []')
        with pytest.raises(KeyError):
            Scheduler(cache, conf=conf)

    def test_failed_bind_repaired_through_running_loop(self):
        """A binder failure must be repaired by the cache's background resync
        loop with no test intervention: run_forever starts cache.run()
        (cache.go:342-384), the failed bind re-enters Pending via
        processResyncTask (cache.go:563-581), and the next cycle re-places
        and successfully re-binds it."""
        import threading
        import time as _time

        class FlakyBinder:
            def __init__(self):
                self.calls = 0
                self.binds = {}

            def bind(self, pod, hostname):
                self.calls += 1
                if self.calls == 1:
                    raise RuntimeError("apiserver down")
                self.binds[f"{pod.namespace}/{pod.name}"] = hostname

        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1")],
            pods=[build_pod("c1", "p0", None, PodPhase.PENDING,
                            {"cpu": 1000, "memory": GiB})],
        )
        binder = FlakyBinder()
        cache.binder = binder
        sched = Scheduler(cache, schedule_period=0.05)
        t = threading.Thread(target=sched.run_forever, daemon=True)
        t.start()
        try:
            deadline = _time.monotonic() + 10.0
            while _time.monotonic() < deadline and not binder.binds:
                _time.sleep(0.05)
        finally:
            sched.stop()
            t.join(timeout=5.0)
        assert binder.binds == {"c1/p0": "n1"}
        assert binder.calls >= 2
        assert cache.err_tasks == []


class TestFitErrorDiagnostics:
    def test_unplaced_task_gets_fit_errors_and_pod_condition(self):
        """allocate.go:151-155 FitErrors + cache.go:500-525,688-711
        taskUnschedulable: an unplaceable pending task ends the cycle with a
        reason histogram, a PodScheduled=False condition, and a
        FailedScheduling event."""
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg1", namespace="c1", min_member=1,
                                 queue="default")],
            nodes=[build_node("n1", cpu=1000, mem=GiB, pods=10)],
            pods=[build_pod("c1", "big", None, PodPhase.PENDING,
                            {"cpu": 8000, "memory": GiB}, group_name="pg1")],
        )
        run_actions(cache, action_names=["allocate"])
        job = cache.jobs["c1/pg1"]
        assert len(cache.binder.binds) == 0
        # FitErrors histogram recorded on the session clone and surfaced as a
        # pod condition through record_job_status_event at gang close
        assert cache.pod_conditions["c1/big"]["reason"] == "Unschedulable"
        msg = cache.pod_conditions["c1/big"]["message"]
        assert "Insufficient resources" in msg and "/1 nodes are available" in msg
        assert any(e[0] == "FailedScheduling" for e in cache.events)
        # PodGroup got the Unschedulable condition (gang.go:132-175)
        assert any(c.type == "Unschedulable" and c.status == "True"
                   for c in job.pod_group.conditions)

    def test_condition_update_deduplicated(self):
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg1", namespace="c1", min_member=1,
                                 queue="default")],
            nodes=[build_node("n1", cpu=1000, mem=GiB, pods=10)],
            pods=[build_pod("c1", "big", None, PodPhase.PENDING,
                            {"cpu": 8000, "memory": GiB}, group_name="pg1")],
        )
        run_actions(cache, action_names=["allocate"])
        n_events = len([e for e in cache.events if e[0] == "FailedScheduling"])
        run_actions(cache, action_names=["allocate"])  # second cycle, same state
        n_events2 = len([e for e in cache.events if e[0] == "FailedScheduling"])
        assert n_events2 == n_events  # no-op condition writes suppressed

    def test_failed_evict_repaired_through_running_loop(self):
        """An evictor failure queues the victim for resync
        (cache.go:432-441); the background repair loop restores it from the
        pod store and a later cycle re-evicts successfully."""
        import threading
        import time as _time

        class FlakyEvictor:
            def __init__(self):
                self.calls = 0
                self.evicts = []

            def evict(self, pod):
                self.calls += 1
                if self.calls == 1:
                    raise RuntimeError("apiserver down")
                self.evicts.append(f"{pod.namespace}/{pod.name}")

        cache = build_cache(
            queues=["default"],
            pod_groups=[
                PodGroup(name="low", namespace="c1", min_member=1, queue="default"),
                PodGroup(name="high", namespace="c1", min_member=1,
                         queue="default"),
            ],
            nodes=[build_node("n1", cpu=2000, mem=4 * GiB, pods=10)],
            pods=[
                build_pod("c1", "low-1", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
                build_pod("c1", "low-2", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
                build_pod("c1", "high-1", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="high",
                          priority=100),
            ],
        )
        evictor = FlakyEvictor()
        cache.evictor = evictor
        # enqueue must run so the starved job's Pending-phase PodGroup is
        # promoted to Inqueue — preempt skips Pending-phase podgroups
        # (preempt.go:59-63), exactly like the reference's shipped conf order
        conf_text = TWO_TIER_CONF.replace(
            '"allocate, backfill"', '"enqueue, allocate, preempt"'
        )
        sched = Scheduler(cache, conf=parse_scheduler_conf(conf_text),
                          schedule_period=0.05)
        t = threading.Thread(target=sched.run_forever, daemon=True)
        t.start()
        try:
            deadline = _time.monotonic() + 10.0
            while _time.monotonic() < deadline and not evictor.evicts:
                _time.sleep(0.05)
        finally:
            sched.stop()
            t.join(timeout=5.0)
        assert evictor.evicts and evictor.evicts[0].startswith("c1/low-")
        assert evictor.calls >= 2
        assert cache.err_tasks == []


class TestQueueStatusWriteback:
    """QueueStatus podgroup-phase counts (types.go:195-204) write through
    the StatusUpdater seam at close — BEYOND the reference, which declares
    the fields but never fills them (the filler controller arrived later,
    in Volcano). Deltas only; a queue whose podgroups all leave zeroes out."""

    def test_counts_written_and_delta_suppressed(self):
        from tests.fixtures import GiB, build_cache, build_node, build_pod
        from kube_batch_tpu.api.pod import PodGroup
        from kube_batch_tpu.api.types import PodPhase

        pods = [
            build_pod("c1", f"g-{i}", None, PodPhase.PENDING,
                      {"cpu": 1000, "memory": GiB}, group_name="g")
            for i in range(2)
        ]
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="g", namespace="c1", min_member=2)],
            nodes=[build_node("n1", cpu=4000, mem=16 * GiB)],
            pods=pods,
        )
        run_actions(cache, action_names=["allocate"])
        st = cache.status_updater.queue_statuses
        assert st["default"] == {"pending": 0, "running": 1, "unknown": 0,
                                 "inqueue": 0}
        # unchanged counts suppress the write: clear the record and re-run
        cache.status_updater.queue_statuses.clear()
        run_actions(cache, action_names=["allocate"])
        assert "default" not in cache.status_updater.queue_statuses

    def test_emptied_queue_zeroes_out(self):
        from tests.fixtures import GiB, build_cache, build_node, build_pod
        from kube_batch_tpu.api.pod import PodGroup
        from kube_batch_tpu.api.types import PodPhase

        pod = build_pod("c1", "solo", None, PodPhase.PENDING,
                        {"cpu": 1000, "memory": GiB}, group_name="g")
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="g", namespace="c1", min_member=1)],
            nodes=[build_node("n1", cpu=4000, mem=16 * GiB)],
            pods=[pod],
        )
        run_actions(cache, action_names=["allocate"])
        assert cache.status_updater.queue_statuses["default"]["running"] == 1
        cache.delete_pod(cache.pods["c1/solo"])
        cache.delete_pod_group("c1/g")
        run_actions(cache, action_names=["allocate"])
        assert cache.status_updater.queue_statuses["default"] == {
            "pending": 0, "running": 0, "unknown": 0, "inqueue": 0,
        }

    def test_gate_dropped_gang_still_counts_pending(self):
        """A gang-invalid job (dropped from the session at open) keeps its
        Pending podgroup in the QueueStatus counts — counts are by phase,
        not session membership."""
        from tests.fixtures import GiB, build_cache, build_node, build_pod
        from kube_batch_tpu.api.pod import PodGroup
        from kube_batch_tpu.api.types import PodPhase

        # minMember=3 but only 1 pod exists → JobValid drops it at open
        pod = build_pod("c1", "g-0", None, PodPhase.PENDING,
                        {"cpu": 1000, "memory": GiB}, group_name="g")
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="g", namespace="c1", min_member=3)],
            nodes=[build_node("n1", cpu=4000, mem=16 * GiB)],
            pods=[pod],
        )
        run_actions(cache, action_names=["allocate"])
        assert cache.binder.binds == {}
        st = cache.status_updater.queue_statuses
        assert st["default"]["pending"] == 1, st
