"""Query plane (serve/ + ops/probe.py): oracle exactness vs the committed
solves, lease consistency under a concurrently mutating cycle, micro-batcher
deadline/overflow behavior under a stubbed clock, sharded-probe bit
equivalence, and the /v1/whatif HTTP surface.

The oracle tests are the subsystem's contract: a gang the probe reports
feasible at nodes X on a frozen snapshot must bind to EXACTLY X when
actually submitted (same rows, same tie-breaks, same machinery), and an
infeasible verdict must carry the same fit-error histogram the committed
cycle would record."""

from __future__ import annotations

import itertools
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod, PodGroup, Queue
from kube_batch_tpu.api.types import PodPhase
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.framework.interface import get_action
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu.serve.batcher import MicroBatcher, QueueFull
from kube_batch_tpu.serve.lease import LeaseBroker, SnapshotLease
from kube_batch_tpu.serve.plane import QueryPlane, WhatifError

from fixtures import GiB, build_cache, build_node, build_pod

CONF = load_scheduler_conf(None)


def _run(cache, names=("allocate",)):
    ssn = open_session(cache, CONF.tiers)
    try:
        for name in names:
            get_action(name).execute(ssn)
    finally:
        close_session(ssn)
    cache.flush_binds()


def _probe(qp: QueryPlane, body: dict) -> dict:
    """Submit one request and drive the flush synchronously (the test
    planes run with start_thread=False)."""
    fut = qp.submit(body)
    qp.batcher.tick(now=qp.batcher.clock.monotonic() + 1e6)
    return fut.result(timeout=60)


@pytest.fixture
def plane_factory():
    planes = []

    def make(cache, **kw):
        kw.setdefault("start_thread", False)
        qp = QueryPlane(cache, **kw)
        planes.append(qp)
        return qp

    yield make
    for qp in planes:
        qp.close()


# ==========================================================================
# oracle exactness: frozen snapshot — probe answers vs the committed solve
# ==========================================================================


class TestWhatifOracle:
    def _heterogeneous_cache(self):
        """Nodes of varied size with varied running load — scores differ
        per node, so placement is a real decision, not a degenerate tie."""
        nodes = [
            build_node("n0", cpu=8000, mem=16 * GiB),
            build_node("n1", cpu=4000, mem=8 * GiB),
            build_node("n2", cpu=16000, mem=32 * GiB),
            build_node("n3", cpu=8000, mem=16 * GiB),
            build_node("n4", cpu=2000, mem=4 * GiB),
        ]
        pods = [
            build_pod("c1", "r0", "n0", PodPhase.RUNNING,
                      {"cpu": 6000, "memory": 4 * GiB}, group_name="run0"),
            build_pod("c1", "r1", "n2", PodPhase.RUNNING,
                      {"cpu": 2000, "memory": 2 * GiB}, group_name="run0"),
            build_pod("c1", "r2", "n3", PodPhase.RUNNING,
                      {"cpu": 7000, "memory": GiB}, group_name="run1"),
        ]
        return build_cache(
            queues=[Queue(name="default", weight=1)],
            pod_groups=[
                PodGroup(name="run0", namespace="c1", min_member=1,
                         queue="default"),
                PodGroup(name="run1", namespace="c1", min_member=1,
                         queue="default"),
            ],
            nodes=nodes,
            pods=pods,
        )

    def _submit_gang(self, cache, count, requests, *, priority=0,
                     selector=None, min_member=None):
        cache.add_pod_group(PodGroup(
            name="probe-pg", namespace="c1",
            min_member=min_member if min_member is not None else count,
            queue="default",
        ))
        for i in range(count):
            cache.add_pod(build_pod(
                "c1", f"probe-{i}", None, PodPhase.PENDING, dict(requests),
                group_name="probe-pg", priority=priority,
                node_selector=selector or {},
            ))

    def test_feasible_gang_binds_exactly_at_probed_nodes(self, plane_factory):
        cache = self._heterogeneous_cache()
        qp = plane_factory(cache)
        _run(cache)  # publishes the lease for the frozen state
        resp = _probe(qp, {
            "queue": "default", "count": 3,
            "requests": {"cpu": 1500, "memory": 2 * GiB},
        })
        assert resp["feasible"] and resp["committed"]
        assert all(n is not None for n in resp["nodes"])

        # now ACTUALLY submit the same gang and run the real allocate path
        self._submit_gang(cache, 3, {"cpu": 1500, "memory": 2 * GiB})
        _run(cache)
        binds = dict(cache.binder.binds)
        got = [binds[f"c1/probe-{i}"] for i in range(3)]
        assert got == resp["nodes"], (
            "probe promised member->node placement must bind verbatim"
        )

    def test_min_available_above_count_cannot_commit(self, plane_factory):
        """min_available > count is a gang that can never reach readiness:
        the commit gate must see the REAL value (no clamp to count), so
        committed is false — matching the real gang discard, which reverts
        exactly such placements and binds nothing."""
        cache = self._heterogeneous_cache()
        qp = plane_factory(cache)
        _run(cache)
        resp = _probe(qp, {
            "queue": "default", "count": 2, "min_available": 5,
            "requests": {"cpu": 500, "memory": GiB},
        })
        assert not resp["committed"], (
            "a 2-member gang with minAvailable=5 must never probe committed"
        )
        # oracle: the real submission's gang discard binds nothing
        self._submit_gang(cache, 2, {"cpu": 500, "memory": GiB},
                          min_member=5)
        _run(cache)
        assert not any(k.startswith("c1/probe-")
                       for k in dict(cache.binder.binds)), (
            "committed gang discard must revert the under-min placement"
        )

    def test_pure_tie_break_case_matches(self, plane_factory):
        """Identical nodes: placement is decided ENTIRELY by the per-(row,
        node) tie hash — the peek_task_rows row oracle is what makes the
        probe land on the committed solve's nodes."""
        cache = build_cache(
            queues=[Queue(name="default", weight=1)],
            nodes=[build_node(f"t{i}", cpu=8000, mem=16 * GiB)
                   for i in range(6)],
        )
        qp = plane_factory(cache)
        _run(cache)
        resp = _probe(qp, {
            "queue": "default", "count": 4,
            "requests": {"cpu": 1000, "memory": GiB},
        })
        assert resp["feasible"]
        self._submit_gang(cache, 4, {"cpu": 1000, "memory": GiB})
        _run(cache)
        binds = dict(cache.binder.binds)
        assert [binds[f"c1/probe-{i}"] for i in range(4)] == resp["nodes"]

    def test_infeasible_reason_matches_committed_fit_errors(
            self, plane_factory):
        cache = self._heterogeneous_cache()
        qp = plane_factory(cache)
        _run(cache)
        resp = _probe(qp, {
            "queue": "default", "count": 1,
            "requests": {"cpu": 1000, "memory": GiB},
            "node_selector": {"zone": "nowhere"},
        })
        assert not resp["feasible"]
        assert resp["unplaced"] == 1

        self._submit_gang(cache, 1, {"cpu": 1000, "memory": GiB},
                          selector={"zone": "nowhere"})
        _run(cache)
        assert "c1/probe-0" not in dict(cache.binder.binds)
        job = next(j for j in cache.jobs.values() if j.name == "probe-pg")
        (fe,) = job.nodes_fit_errors.values()
        committed = dict(fe._hist)
        assert resp["fit_errors"] == committed

    def test_resource_infeasible_reason_matches(self, plane_factory):
        cache = self._heterogeneous_cache()
        qp = plane_factory(cache)
        _run(cache)
        resp = _probe(qp, {
            "queue": "default", "count": 1,
            "requests": {"cpu": 64000, "memory": GiB},
        })
        assert not resp["feasible"]
        self._submit_gang(cache, 1, {"cpu": 64000, "memory": GiB})
        _run(cache)
        job = next(j for j in cache.jobs.values() if j.name == "probe-pg")
        (fe,) = job.nodes_fit_errors.values()
        committed = dict(fe._hist)
        assert resp["fit_errors"] == committed

    def test_eviction_probe_matches_committed_preempt(self, plane_factory):
        """The high-priority starved-gang scenario (TestPreemptAction):
        the probe's hypothetical eviction set must equal what the real
        preempt action then evicts, and the claim node must match."""
        cache = build_cache(
            queues=["default"],
            pod_groups=[
                PodGroup(name="low", namespace="c1", min_member=1,
                         queue="default"),
            ],
            nodes=[build_node("n1", cpu=2000, mem=4 * GiB, pods=10)],
            pods=[
                build_pod("c1", "low-1", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
                build_pod("c1", "low-2", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
            ],
        )
        qp = plane_factory(cache)
        _run(cache)
        resp = _probe(qp, {
            "queue": "default", "count": 1, "priority": 100,
            "requests": {"cpu": 1000, "memory": GiB},
            "evictions": True,
        })
        assert not resp["feasible"]  # node is full — no idle placement
        ev = resp["evictions"]
        assert ev["covered"]
        assert ev["claim_nodes"] == ["n1"]
        assert len(ev["victims"]) == 1 and ev["victims"][0].startswith("c1/low-")

        self._submit_gang(cache, 1, {"cpu": 1000, "memory": GiB},
                          priority=100)
        _run(cache, names=("allocate", "preempt"))
        assert sorted(cache.evictor.evicts) == ev["victims"]

    def test_no_eviction_when_gang_would_break(self, plane_factory):
        """gang slack: victims below their job's minAvailable are off
        limits — probe and committed preempt agree on the refusal."""
        cache = build_cache(
            queues=["default"],
            pod_groups=[
                PodGroup(name="low", namespace="c1", min_member=2,
                         queue="default"),
            ],
            nodes=[build_node("n1", cpu=2000, mem=4 * GiB, pods=10)],
            pods=[
                build_pod("c1", "low-1", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
                build_pod("c1", "low-2", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
            ],
        )
        qp = plane_factory(cache)
        _run(cache)
        resp = _probe(qp, {
            "queue": "default", "count": 1, "priority": 100,
            "requests": {"cpu": 1000, "memory": GiB},
            "evictions": True,
        })
        assert resp["evictions"]["victims"] == []
        assert not resp["evictions"]["covered"]

        self._submit_gang(cache, 1, {"cpu": 1000, "memory": GiB},
                          priority=100)
        _run(cache, names=("allocate", "preempt"))
        assert cache.evictor.evicts == []

    def test_admission_verdict_mirrors_enqueue_capability(
            self, plane_factory):
        cache = self._heterogeneous_cache()
        qp = plane_factory(cache)
        _run(cache)
        ok = _probe(qp, {
            "queue": "default", "count": 1,
            "requests": {"cpu": 100, "memory": GiB},
            "min_resources": {"cpu": 2000, "memory": 2 * GiB},
        })
        assert ok["enqueue_admitted"]
        # cluster total cpu = 38000, ×1.2 = 45600; used = 15000 → idle 30600
        too_big = _probe(qp, {
            "queue": "default", "count": 1,
            "requests": {"cpu": 100, "memory": GiB},
            "min_resources": {"cpu": 99000},
        })
        assert not too_big["enqueue_admitted"]

    def test_idle_and_empty_cluster_still_serve(self, plane_factory):
        """Serving deployments publish a lease even when the cycle has
        nothing to solve — an idle cluster is exactly when capacity
        planning what-ifs arrive."""
        cache = build_cache(
            queues=[Queue(name="default", weight=1)],
            nodes=[build_node("i0", cpu=4000, mem=8 * GiB)],
        )
        qp = plane_factory(cache)
        _run(cache)  # no jobs at all
        resp = _probe(qp, {"queue": "default", "count": 1,
                           "requests": {"cpu": 1000, "memory": GiB}})
        assert resp["feasible"] and resp["nodes"] == ["i0"]
        # a steadily idle cluster republishes only when ingest moves the
        # version — the snapshot rebuild is paid once, not every period
        published = qp.broker.published
        _run(cache)
        assert qp.broker.published == published
        again = _probe(qp, {"queue": "default", "count": 1,
                            "requests": {"cpu": 1000, "memory": GiB}})
        assert again["nodes"] == ["i0"]
        assert again["snapshot_version"] == resp["snapshot_version"]

    def test_request_validation(self, plane_factory):
        cache = self._heterogeneous_cache()
        qp = plane_factory(cache)
        with pytest.raises(WhatifError):
            qp.submit({"count": 0})
        with pytest.raises(WhatifError):
            qp.submit({"count": 10_000})
        with pytest.raises(WhatifError):
            qp.submit({"count": 1, "requests": "not-a-map"})
        with pytest.raises(WhatifError):
            qp.submit({"count": 1, "requests": {"cpu": "abc"}})
        # malformed per-request fields must 400 at submit — never inside
        # the batch flush where they would fail the whole window
        with pytest.raises(WhatifError):
            qp.submit({"count": 1, "priority": "high"})
        with pytest.raises(WhatifError):
            qp.submit({"count": 1, "tolerations": "not-a-list"})
        with pytest.raises(WhatifError):
            qp.submit({"count": 1, "tolerations": [{"bogus": 1}]})
        with pytest.raises(WhatifError):
            qp.submit({"count": 1, "min_resources": {"cpu": "abc"}})
        # i32-overflowing integers must 400 here too — inside the flush
        # they would OverflowError the batch encode and 500 the window
        with pytest.raises(WhatifError):
            qp.submit({"count": 1, "min_available": 2**40})
        with pytest.raises(WhatifError):
            qp.submit({"count": 1, "priority": 2**40})


class TestQueueAdmissionVeto:
    """The queue-state half of the admission verdict: JobEnqueueable
    (plugins/proportion.py) vetoes a gang whose min_resources plus the
    queue's current allocation exceed its Capability — the probe must
    apply the same veto, with the same quanta tolerance, as the
    committed enqueue action."""

    def _capped_cache(self):
        # queue "capped" holds 6000 cpu / 4 GiB of running load against a
        # 10000-cpu capability; the CLUSTER has far more idle than that,
        # so only the queue veto separates the verdicts below
        return build_cache(
            queues=[Queue(name="capped", weight=1,
                          capability={"cpu": 10000.0, "memory": 64 * GiB,
                                      "pods": 16.0})],
            pod_groups=[PodGroup(name="run0", namespace="c1", min_member=1,
                                 queue="capped")],
            nodes=[build_node(f"n{i}", cpu=16000, mem=64 * GiB, pods=64)
                   for i in range(2)],
            pods=[build_pod("c1", "r0", "n0", PodPhase.RUNNING,
                            {"cpu": 6000, "memory": 4 * GiB},
                            group_name="run0")],
        )

    def test_queue_capability_vetoes_over_cap_min_resources(
            self, plane_factory):
        cache = self._capped_cache()
        qp = plane_factory(cache)
        _run(cache)
        base = {"queue": "capped", "count": 1,
                "requests": {"cpu": 100, "memory": GiB}}
        # 6000 allocated + 3000 = 9000 ≤ 10000 → admitted
        under = _probe(qp, dict(base, min_resources={"cpu": 3000}))
        assert under["enqueue_admitted"]
        # 6000 + 8000 = 14000 > 10000 → queue veto, even though the
        # cluster-wide capability gate alone (idle ≈ 32400) would admit
        over = _probe(qp, dict(base, min_resources={"cpu": 8000}))
        assert not over["enqueue_admitted"]
        assert over["feasible"], "the veto is advisory, not a placement gate"

    def test_veto_honors_quanta_tolerance(self, plane_factory):
        """Resource.less_equal admits need−cap below the per-dim quantum
        (MIN_MILLI_CPU = 10); the columnar verdict must agree at the
        boundary."""
        cache = self._capped_cache()
        qp = plane_factory(cache)
        _run(cache)
        base = {"queue": "capped", "count": 1,
                "requests": {"cpu": 100, "memory": GiB}}
        within = _probe(qp, dict(base, min_resources={"cpu": 4005}))
        assert within["enqueue_admitted"]      # need 10005, over by 5 < 10
        beyond = _probe(qp, dict(base, min_resources={"cpu": 4020}))
        assert not beyond["enqueue_admitted"]  # need 10020, over by 20

    def test_unknown_queue_skips_the_veto(self, plane_factory):
        """A queue the snapshot does not know (proportion's attrs map has
        no entry) cannot veto — only the cluster capability gate applies,
        exactly like jobEnqueueableFns finding no attr."""
        cache = self._capped_cache()
        qp = plane_factory(cache)
        _run(cache)
        resp = _probe(qp, {"queue": "ghost", "count": 1,
                           "requests": {"cpu": 100, "memory": GiB},
                           "min_resources": {"cpu": 8000}})
        assert resp["enqueue_admitted"]

    def test_verdict_mirrors_committed_enqueue_action(self, plane_factory):
        """Probe verdicts vs the real enqueue action on the same state:
        the over-cap gang stays Pending, the under-cap gang goes InQueue —
        matching enqueue_admitted per gang."""
        from kube_batch_tpu.api.types import PodGroupPhase

        cache = self._capped_cache()
        qp = plane_factory(cache)
        _run(cache)
        verdicts = {}
        for name, cpu in (("over", 8000.0), ("under", 3000.0)):
            verdicts[name] = _probe(qp, {
                "queue": "capped", "count": 1,
                "requests": {"cpu": 100, "memory": GiB},
                "min_resources": {"cpu": cpu},
            })["enqueue_admitted"]
            cache.add_pod_group(PodGroup(
                name=name, namespace="c1", min_member=1, queue="capped",
                min_resources={"cpu": cpu}, phase=PodGroupPhase.PENDING,
            ))
            cache.add_pod(build_pod(
                "c1", f"{name}-0", None, PodPhase.PENDING,
                {"cpu": 100, "memory": GiB}, group_name=name))
        assert verdicts == {"over": False, "under": True}
        _run(cache, names=("enqueue",))
        phases = {name: cache.jobs[f"c1/{name}"].pod_group.phase
                  for name in ("over", "under")}
        assert phases["over"] == PodGroupPhase.PENDING
        assert phases["under"] == PodGroupPhase.INQUEUE


class TestPeekTaskRows:
    def test_peek_matches_alloc_order_across_free_and_growth(self):
        """peek(k) must predict alloc() exactly — free-list LIFO first,
        then ascending grown rows — or the probe's tie-hash oracle drifts
        from the rows a submitted gang actually lands on."""
        from kube_batch_tpu.api.columns import _Axis

        ax = _Axis(floor=4)
        for _ in range(2):
            ax.alloc()
        ax.free(0)  # freed row returns LIFO
        want = ax.peek(8)  # crosses the growth boundary (cap=4)
        got = []
        for _ in range(8):
            row = ax.alloc()
            if row is None:  # the ColumnStore growth path
                ax.on_grown(ax.grown_cap())
                row = ax.alloc()
            got.append(row)
        assert want == got


# ==========================================================================
# lease consistency — concurrent with a mutating cycle
# ==========================================================================


def _mk_lease(version, snap="snap"):
    return SnapshotLease(
        snap=snap, meta=None, version=version, config=None,
        evict_config=None, mesh=None, probe_rows=(), queue_rows={},
    )


class TestLeaseBroker:
    def test_stale_publish_ignored(self):
        broker = LeaseBroker()
        broker.publish(_mk_lease(5))
        broker.publish(_mk_lease(3))  # stale publisher — dropped
        assert broker.current().version == 5
        broker.publish(_mk_lease(6))
        assert broker.current().version == 6

    def test_current_times_out_without_publisher(self):
        broker = LeaseBroker()
        t0 = time.monotonic()
        assert broker.current(timeout=0.05) is None
        assert time.monotonic() - t0 < 5

    def test_swap_guard_excludes_dispatch(self):
        """A probe dispatch must never overlap the resident swap — the
        no-torn-read guarantee on donating backends."""
        broker = LeaseBroker()
        broker.publish(_mk_lease(1))
        order = []
        in_swap = threading.Event()
        release = threading.Event()

        def swapper():
            with broker.swap_guard():
                order.append("swap_start")
                in_swap.set()
                release.wait(timeout=5)
                order.append("swap_end")

        t = threading.Thread(target=swapper)
        t.start()
        assert in_swap.wait(timeout=5)
        threading.Timer(0.05, release.set).start()
        with broker.dispatch(timeout=5):
            order.append("dispatch")
        t.join(timeout=5)
        assert order == ["swap_start", "swap_end", "dispatch"]

    def test_swap_guard_retires_lease_on_donating_backends(self, monkeypatch):
        from kube_batch_tpu.serve import lease as lease_mod

        monkeypatch.setattr(lease_mod, "_donation_active", lambda: True)
        broker = LeaseBroker()
        broker.publish(_mk_lease(1))
        with broker.swap_guard():
            assert broker.current() is None  # buffers about to be donated
        assert broker.retired == 1
        broker.publish(_mk_lease(2))
        assert broker.current().version == 2

    def test_swap_guard_keeps_lease_on_cpu(self, monkeypatch):
        from kube_batch_tpu.serve import lease as lease_mod

        monkeypatch.setattr(lease_mod, "_donation_active", lambda: False)
        broker = LeaseBroker()
        broker.publish(_mk_lease(1))
        with broker.swap_guard():
            pass
        assert broker.current().version == 1
        assert broker.retired == 0

    def test_donating_swap_waits_for_inflight_dispatch(self, monkeypatch):
        """A dispatch's device round-trip counts as an in-flight READER:
        a donating swap must wait it out before invalidating the buffers
        (the lock itself is no longer held across the round-trip)."""
        from kube_batch_tpu.serve import lease as lease_mod

        monkeypatch.setattr(lease_mod, "_donation_active", lambda: True)
        broker = LeaseBroker()
        broker.publish(_mk_lease(1))
        order = []
        reading = threading.Event()
        release = threading.Event()

        def reader():
            with broker.dispatch(timeout=5) as lease:
                assert lease is not None
                order.append("read_start")
                reading.set()
                release.wait(timeout=5)
                order.append("read_end")

        t = threading.Thread(target=reader)
        t.start()
        assert reading.wait(timeout=5)
        threading.Timer(0.05, release.set).start()
        with broker.swap_guard():
            order.append("swap")
        t.join(timeout=5)
        assert order == ["read_start", "read_end", "swap"]

    def test_publish_never_blocks_behind_dispatch(self):
        """The broker lock is bookkeeping-only: a publish lands while a
        dispatch's (slow) device round-trip is still in flight."""
        broker = LeaseBroker()
        broker.publish(_mk_lease(1))
        in_read = threading.Event()
        release = threading.Event()

        def reader():
            with broker.dispatch(timeout=5):
                in_read.set()
                release.wait(timeout=5)

        t = threading.Thread(target=reader)
        t.start()
        assert in_read.wait(timeout=5)
        broker.publish(_mk_lease(2))  # must not deadlock behind the reader
        assert broker.current().version == 2
        release.set()
        t.join(timeout=5)


class TestLeaseUnderChurn:
    def test_versions_monotonic_and_answers_valid_under_live_cycles(self):
        """Whatifs served WHILE cycles mutate the cache: every answer
        carries a valid version token, tokens never regress, and every
        response decodes cleanly (no torn snapshot)."""
        cache = build_cache(
            queues=[Queue(name="default", weight=1)],
            nodes=[build_node(f"c{i}", cpu=8000, mem=16 * GiB)
                   for i in range(8)],
        )
        qp = QueryPlane(cache, max_batch=4, window_s=0.001,
                        start_thread=True)
        try:
            _run(cache)
            stop = threading.Event()
            seen: list = []
            errors: list = []

            def client():
                while not stop.is_set():
                    try:
                        fut = qp.submit({
                            "queue": "default", "count": 2,
                            "requests": {"cpu": 500, "memory": GiB},
                        })
                        resp = fut.result(timeout=30)
                        assert isinstance(resp["feasible"], bool)
                        assert len(resp["nodes"]) == 2
                        seen.append(resp["snapshot_version"])
                    except Exception as e:  # noqa: BLE001
                        errors.append(repr(e))
                        return

            threads = [threading.Thread(target=client) for _ in range(3)]
            for t in threads:
                t.start()
            serial = itertools.count()
            for _ in range(6):  # churning cycles concurrent with serving
                j = next(serial)
                cache.add_pod_group(PodGroup(
                    name=f"churn{j}", namespace="w", min_member=1,
                    queue="default"))
                cache.add_pod(Pod(
                    name=f"churn{j}-0", namespace="w",
                    requests={"cpu": 250.0, "memory": float(GiB)},
                    annotations={GROUP_NAME_ANNOTATION: f"churn{j}"},
                    phase=PodPhase.PENDING, creation_index=50_000 + j,
                ))
                _run(cache)
            stop.set()
            for t in threads:
                t.join(timeout=60)
            assert not errors, errors
            assert seen, "clients never got an answer"
            published = qp.broker.current().version
            assert max(seen) <= published
            # within each client the token sequence is non-decreasing —
            # interleave-safe because each client appends its own results
            # sequentially; global max-so-far must also never regress
            hi = 0
            for v in seen:
                assert v >= 0
                hi = max(hi, v)
            assert hi == max(seen)
        finally:
            qp.close()

    def test_publish_failure_degrades_serving_not_cycle(self, monkeypatch):
        """A broken query plane must never take the scheduling cycle down
        (the write path outranks serving)."""
        cache = build_cache(
            queues=[Queue(name="default", weight=1)],
            nodes=[build_node("d0", cpu=4000, mem=8 * GiB)],
        )
        qp = QueryPlane(cache, start_thread=False)
        try:
            monkeypatch.setattr(
                qp, "publish_session",
                lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
            )
            cache.add_pod_group(PodGroup(
                name="pg", namespace="c1", min_member=1, queue="default"))
            cache.add_pod(build_pod(
                "c1", "p0", None, PodPhase.PENDING,
                {"cpu": 1000, "memory": GiB}, group_name="pg"))
            _run(cache)  # must not raise
            assert dict(cache.binder.binds)["c1/p0"] == "d0"
        finally:
            qp.close()

    def test_swapping_actions_republish_retired_lease(
            self, plane_factory, monkeypatch):
        """On donating backends EVERY resident swap retires the lease —
        and reclaim/backfill/preempt all swap AFTER allocate publishes.
        Each swapping action must republish right after its dispatch, so a
        full pipeline cycle ends with a LIVE lease instead of leaving
        serving dark until the next cycle's allocate."""
        from kube_batch_tpu.serve import lease as lease_mod

        monkeypatch.setattr(lease_mod, "_donation_active", lambda: True)
        # full node of low-priority RUNNING work + a starved high-priority
        # gang: allocate can't place it, so preempt dispatches its solve
        # (a second resident swap after allocate's publish)
        cache = build_cache(
            queues=["default"],
            pod_groups=[
                PodGroup(name="low", namespace="c1", min_member=1,
                         queue="default"),
                PodGroup(name="hi", namespace="c1", min_member=1,
                         queue="default"),
            ],
            nodes=[build_node("n1", cpu=2000, mem=4 * GiB, pods=10)],
            pods=[
                build_pod("c1", "low-1", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
                build_pod("c1", "low-2", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
                build_pod("c1", "hi-0", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="hi",
                          priority=100),
            ],
        )
        qp = plane_factory(cache)
        _run(cache, names=("enqueue", "reclaim", "allocate", "preempt"))
        # preempt's swap retired allocate's publish... and republished
        assert qp.broker.retired >= 1, "scenario never exercised retirement"
        lease = qp.broker.current()
        assert lease is not None, (
            "query plane left leaseless after the cycle's last swap"
        )
        # ...and the republished lease actually serves (CPU buffers are
        # still valid — only the broker's donation gate was patched)
        resp = _probe(qp, {"queue": "default", "count": 1,
                           "requests": {"cpu": 1000, "memory": GiB}})
        assert resp["snapshot_version"] == lease.version


# ==========================================================================
# micro-batcher — stubbed clock
# ==========================================================================


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def monotonic(self):
        return self.t


class TestMicroBatcher:
    def _mk(self, flushed, **kw):
        clock = FakeClock()
        kw.setdefault("max_batch", 4)
        kw.setdefault("window_s", 0.010)
        kw.setdefault("max_queue", 8)
        b = MicroBatcher(lambda batch: flushed.append(batch), clock=clock,
                        start_thread=False, **kw)
        return b, clock

    def test_deadline_flush(self):
        flushed = []
        b, clock = self._mk(flushed)
        b.submit("r1")
        assert b.tick() == 0          # window not elapsed
        clock.t = 0.009
        assert b.tick() == 0
        clock.t = 0.010               # deadline from FIRST enqueue
        assert b.tick() == 1
        assert [r for r, _f in flushed[0]] == ["r1"]

    def test_bucket_fill_flushes_immediately(self):
        flushed = []
        b, clock = self._mk(flushed)
        for i in range(4):
            b.submit(f"r{i}")
        assert b.tick() == 4          # bucket full — no window wait
        assert b.depth() == 0

    def test_oversize_burst_drains_in_buckets(self):
        flushed = []
        b, clock = self._mk(flushed)
        for i in range(7):
            b.submit(f"r{i}")
        assert b.tick() == 4
        clock.t = 1.0
        assert b.tick() == 3
        assert [len(x) for x in flushed] == [4, 3]

    def test_overflow_rejects_immediately(self):
        flushed = []
        b, clock = self._mk(flushed, max_queue=2)
        f1, f2 = b.submit("a"), b.submit("b")
        f3 = b.submit("c")            # over capacity — shed, don't buffer
        assert isinstance(f3.exception(timeout=1), QueueFull)
        assert b.rejected == 1
        assert not f1.done() and not f2.done()  # accepted, still pending
        clock.t = 1.0
        assert b.tick() == 2

    def test_flush_failure_fails_that_batch_only(self):
        calls = []

        def flaky(batch):
            calls.append(batch)
            if len(calls) == 1:
                raise RuntimeError("dispatch exploded")

        clock = FakeClock()
        b = MicroBatcher(flaky, max_batch=2, window_s=0.01, max_queue=8,
                        clock=clock, start_thread=False)
        f1 = b.submit("a")
        clock.t = 1.0
        b.tick()
        assert isinstance(f1.exception(timeout=1), RuntimeError)
        f2 = b.submit("b")
        clock.t = 2.0
        b.tick()
        assert len(calls) == 2  # the batcher kept serving

    def test_stop_drains_pending_futures(self):
        flushed = []
        clock = FakeClock()
        b = MicroBatcher(lambda batch: flushed.append(batch), max_batch=4,
                        window_s=10.0, max_queue=8, clock=clock,
                        start_thread=True)
        fut = b.submit("late")
        b.stop()
        assert isinstance(fut.exception(timeout=5), QueueFull)
        assert b.submit("after-stop").exception(timeout=1) is not None


# ==========================================================================
# sharded probe — bit-exact vs single device, both impls
# ==========================================================================


class TestShardedProbe:
    @pytest.fixture(scope="class")
    def frozen(self):
        """A nearly-full cluster with RUNNING load: one allocate cycle
        binds the synthetic gangs, the binds are promoted to RUNNING, and
        the running podgroups relax to min_member=1 so victims carry gang
        slack — without it every gang sits exactly at minAvailable and the
        eviction probe (correctly) refuses every victim."""
        import dataclasses

        from kube_batch_tpu.actions.allocate import (
            build_session_snapshot,
            session_allocate_config,
        )
        from kube_batch_tpu.testing.synthetic import synthetic_cluster

        cache = synthetic_cluster(n_tasks=400, n_nodes=16, gang_size=4,
                                  n_queues=2, seed=11)
        _run(cache)
        for key, node in sorted(cache.binder.binds.items()):
            cache.update_pod(dataclasses.replace(
                cache.pods[key], phase=PodPhase.RUNNING, node_name=node))
        for _uid, job in sorted(cache.jobs.items()):
            if job.pod_group is not None:
                cache.update_pod_group(
                    dataclasses.replace(job.pod_group, min_member=1))
        ssn = open_session(cache, CONF.tiers)
        try:
            snap, meta = build_session_snapshot(ssn)
            config = session_allocate_config(ssn)._replace(use_pallas=False)
        finally:
            close_session(ssn)
        return snap, config

    def _batch(self, snap, seed=0):
        from kube_batch_tpu.ops.probe import ProbeBatch

        rng = np.random.default_rng(seed)
        T, R = snap.task_req.shape
        W = snap.task_sel_bits.shape[1]
        Wt = snap.task_tol_bits.shape[1]
        B, G = 6, 8
        req = np.zeros((B, G, R), np.float32)
        valid = np.zeros((B, G), bool)
        for b in range(B):
            n = int(rng.integers(1, G + 1))
            valid[b, :n] = True
            # mix: small (feasible), large (infeasible), and node-filling
            # (feasible only via eviction) asks
            req[b, :n, 0] = float(rng.choice([250.0, 3000.0, 7500.0]))
            req[b, :n, 1] = float(2 ** 30)
        batch = ProbeBatch(
            req=req, valid=valid,
            min_avail=np.maximum(valid.sum(1), 1).astype(np.int32),
            queue=(np.arange(B) % 2).astype(np.int32),
            prio=np.full(B, 50, np.int32),
            sel_bits=np.zeros((B, W), np.uint32),
            sel_impossible=np.zeros(B, bool),
            tol_bits=np.zeros((B, Wt), np.uint32),
            min_res=np.zeros((B, R), np.float32),
            has_min_res=np.zeros(B, bool),
        )
        rows = np.arange(T, T + G, dtype=np.int32)
        return batch, rows

    @pytest.mark.slow
    def test_sharded_probe_bit_exact_both_impls(self, frozen):
        import jax

        from kube_batch_tpu.ops.eviction import EvictConfig
        from kube_batch_tpu.ops.probe import probe_solve
        from kube_batch_tpu.parallel.mesh import (
            make_mesh,
            probe_solve_fn,
            snapshot_shardings,
        )

        snap, config = frozen
        batch, rows = self._batch(snap)
        evc = EvictConfig(mode="preempt", victim_gang=True,
                          victim_conformance=True)
        single = probe_solve(snap, batch, rows, config, evc, True)
        assert bool(np.asarray(single.victims).any()), (
            "fixture must exercise the eviction probe"
        )
        mesh = make_mesh(len(jax.devices()))
        dev = jax.device_put(snap, snapshot_shardings(mesh))
        for impl in ("shard_map", "pjit"):
            fn = probe_solve_fn(mesh, config, evc, True, impl=impl)
            with mesh:
                res = fn(dev, batch, rows)
            for f in single._fields:
                assert np.array_equal(
                    np.asarray(getattr(single, f)),
                    np.asarray(getattr(res, f)),
                ), (impl, f)

    @pytest.mark.slow
    def test_no_retrace_across_batch_fill(self, frozen):
        from kube_batch_tpu.ops.eviction import EvictConfig
        from kube_batch_tpu.ops.probe import probe_solve
        from kube_batch_tpu.utils import jitstats

        snap, config = frozen
        evc = EvictConfig(mode="preempt")
        b1, rows = self._batch(snap, seed=1)
        probe_solve(snap, b1, rows, config, evc, False)  # warmup
        before = jitstats.compile_counts().get("probe_solve", 0)
        for seed in (2, 3, 4):  # varying fill, same (B, G) buckets
            bn, rows = self._batch(snap, seed=seed)
            probe_solve(snap, bn, rows, config, evc, False)
        after = jitstats.compile_counts().get("probe_solve", 0)
        assert after == before, "probe retraced across batch fill"


# ==========================================================================
# flush partitioning + pre-warm (serving-latency hygiene)
# ==========================================================================


class TestFlushPartitionAndPrewarm:
    def _cache(self):
        return build_cache(
            queues=[Queue(name="default", weight=1)],
            nodes=[build_node(f"p{i}", cpu=8000, mem=16 * GiB)
                   for i in range(4)],
        )

    def test_mixed_window_splits_by_evictions_flag(self, plane_factory):
        """One --evictions request in a window must not run the eviction
        program for the co-batched plain probes: the flush partitions the
        window into (plain, evictions) sub-dispatches against the SAME
        lease."""
        cache = self._cache()
        qp = plane_factory(cache, max_batch=8)
        _run(cache)
        plain = qp.submit({"queue": "default", "count": 1,
                           "requests": {"cpu": 500, "memory": GiB}})
        evict = qp.submit({"queue": "default", "count": 1,
                           "requests": {"cpu": 500, "memory": GiB},
                           "evictions": True})
        d0 = qp.dispatches
        qp.batcher.tick(now=qp.batcher.clock.monotonic() + 1e6)
        r_plain = plain.result(timeout=120)
        r_evict = evict.result(timeout=120)
        assert qp.dispatches == d0 + 2, (
            "mixed window must split into exactly two dispatches"
        )
        assert "evictions" not in r_plain
        assert "evictions" in r_evict
        # both halves answered against the same lease
        assert r_plain["snapshot_version"] == r_evict["snapshot_version"]

    def test_uniform_window_stays_one_dispatch(self, plane_factory):
        cache = self._cache()
        qp = plane_factory(cache, max_batch=8)
        _run(cache)
        futs = [qp.submit({"queue": "default", "count": 1,
                           "requests": {"cpu": 250, "memory": GiB}})
                for _ in range(4)]
        d0 = qp.dispatches
        qp.batcher.tick(now=qp.batcher.clock.monotonic() + 1e6)
        for f in futs:
            assert f.result(timeout=120)["feasible"]
        assert qp.dispatches == d0 + 1

    def test_cancelled_futures_skipped_at_flush(self, plane_factory):
        """A handler that times out cancels its future (cmd/server.py):
        the flush must not spend a dispatch on a fully-abandoned window,
        and a partially-abandoned one must not count the abandoned request
        in the verdict counters (it would mask an outage as successes)."""
        cache = self._cache()
        qp = plane_factory(cache, max_batch=8)
        _run(cache)
        # fully abandoned window: no dispatch at all
        f0 = qp.submit({"queue": "default", "count": 1,
                        "requests": {"cpu": 500, "memory": GiB}})
        assert f0.cancel()
        d0 = qp.dispatches
        qp.batcher.tick(now=qp.batcher.clock.monotonic() + 1e6)
        assert qp.dispatches == d0, "abandoned window must not dispatch"
        # partially abandoned: live request served, abandoned one uncounted
        gone = qp.submit({"queue": "default", "count": 1,
                          "requests": {"cpu": 500, "memory": GiB}})
        live = qp.submit({"queue": "default", "count": 1,
                          "requests": {"cpu": 500, "memory": GiB}})
        assert gone.cancel()
        served0 = qp.requests_served
        qp.batcher.tick(now=qp.batcher.clock.monotonic() + 1e6)
        assert live.result(timeout=120)["feasible"]
        assert qp.requests_served == served0 + 1

    def test_prewarm_compiles_floor_bucket_off_request_path(
            self, plane_factory):
        from kube_batch_tpu.utils import jitstats

        cache = self._cache()
        qp = plane_factory(cache, prewarm=True)
        _run(cache)  # publish kicks the warm thread
        assert qp._warm_threads, "publish must kick a pre-warm thread"
        for t in qp._warm_threads:
            t.join(timeout=300)
        # the warm dispatch compiled the serving floor bucket but stayed
        # out of the serving counters
        assert qp.dispatches == 0
        compiles0 = jitstats.compile_counts().get("probe_solve", 0)
        assert compiles0 >= 1
        # first REAL request rides the warm cache: no retrace
        resp = _probe(qp, {"queue": "default", "count": 2,
                           "requests": {"cpu": 500, "memory": GiB}})
        assert resp["feasible"]
        assert jitstats.compile_counts().get("probe_solve", 0) == compiles0
        # a republish of the same lease shape must not warm again
        lease = qp.broker.current()
        qp._maybe_prewarm(lease)
        assert len(qp._warm_threads) == 1


# ==========================================================================
# HTTP surface — POST /v1/whatif + metrics counters
# ==========================================================================


class TestWhatifHTTP:
    def _post(self, port, body, path="/v1/whatif"):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    def test_end_to_end_with_metrics(self):
        from urllib.error import HTTPError

        from kube_batch_tpu.cmd.server import AdminServer
        from kube_batch_tpu.metrics import metrics as M

        cache = build_cache(
            queues=[Queue(name="default", weight=1)],
            nodes=[build_node(f"h{i}", cpu=8000, mem=16 * GiB)
                   for i in range(4)],
        )
        # generous dispatch timeout: the handler's future wait is keyed to
        # it, and the FIRST probe at this (B, G) bucket pays a cold compile
        qp = QueryPlane(cache, max_batch=8, window_s=0.002,
                        dispatch_timeout=90, start_thread=True)
        srv = AdminServer(cache, port=0, query_plane=qp)
        srv.start()
        try:
            _run(cache)
            req0 = sum(M.WHATIF_REQUESTS._values.values())
            disp0 = sum(M.WHATIF_DISPATCHES._values.values())
            ok = self._post(srv.port, {
                "queue": "default", "count": 2,
                "requests": {"cpu": 1000, "memory": GiB},
            })
            assert ok["feasible"] and len(ok["nodes"]) == 2
            bad = self._post(srv.port, {
                "queue": "default", "count": 2,
                "requests": {"cpu": 990000, "memory": GiB},
            })
            assert not bad["feasible"] and bad["fit_errors"]
            assert ok["snapshot_version"] == bad["snapshot_version"]

            with pytest.raises(HTTPError) as err:
                self._post(srv.port, {"count": -2})
            assert err.value.code == 400

            assert sum(M.WHATIF_REQUESTS._values.values()) == req0 + 2
            assert sum(M.WHATIF_DISPATCHES._values.values()) > disp0
            rendered = M.render_prometheus()
            assert "volcano_whatif_requests_total" in rendered
            assert "volcano_whatif_batch_size" in rendered
        finally:
            srv.stop()
            qp.close()

    def test_503_when_plane_missing_or_cold(self):
        from urllib.error import HTTPError

        from kube_batch_tpu.cmd.server import AdminServer

        cache = build_cache(
            queues=[Queue(name="default", weight=1)],
            nodes=[build_node("x0", cpu=4000, mem=8 * GiB)],
        )
        srv = AdminServer(cache, port=0)  # no query plane wired
        srv.start()
        try:
            with pytest.raises(HTTPError) as err:
                self._post(srv.port, {"count": 1, "requests": {"cpu": 1}})
            assert err.value.code == 503
        finally:
            srv.stop()

        qp = QueryPlane(cache, start_thread=True, dispatch_timeout=0.05)
        srv = AdminServer(cache, port=0, query_plane=qp)
        srv.start()
        try:
            # no cycle has run — no lease published yet
            with pytest.raises(HTTPError) as err:
                self._post(srv.port, {"count": 1, "requests": {"cpu": 1}})
            assert err.value.code == 503
        finally:
            srv.stop()
            qp.close()


# ==========================================================================
# verdict honesty: per-response `unmodeled: [...]` (guard-plane PR satellite)
# ==========================================================================


class TestUnmodeledHonesty:
    """Probe verdicts whose conf carries preempt gates the eviction probe
    does not model (drf/proportion victim gates), or whose gang only the
    backfill path could bind (all-BestEffort), must say so PER RESPONSE —
    a one-shot process log is invisible to the client that needs it."""

    DRF_TIER1_CONF = """
    actions: "enqueue, reclaim, allocate, backfill, preempt"
    tiers:
    - plugins:
      - name: priority
      - name: gang
      - name: conformance
      - name: drf
    - plugins:
      - name: predicates
      - name: proportion
      - name: nodeorder
    """

    def _cache(self):
        return build_cache(
            queues=[Queue(name="default", weight=1)],
            pod_groups=[],
            nodes=[build_node("n0", cpu=8000, mem=16 * GiB)],
            pods=[],
        )

    def _run_conf(self, cache, conf_text):
        import textwrap

        from kube_batch_tpu.framework.conf import parse_scheduler_conf

        conf = parse_scheduler_conf(textwrap.dedent(conf_text))
        ssn = open_session(cache, conf.tiers)
        try:
            get_action("allocate").execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()

    def test_shipped_conf_plain_probe_has_empty_unmodeled(self, plane_factory):
        cache = self._cache()
        qp = plane_factory(cache)
        _run(cache)
        resp = _probe(qp, {"queue": "default", "count": 1,
                           "requests": {"cpu": 1000, "memory": GiB}})
        assert resp["unmodeled"] == []

    def test_shipped_conf_eviction_probe_has_empty_unmodeled(
        self, plane_factory
    ):
        # the shipped conf's first voting preempt tier is gang+conformance
        # — fully modeled, so the field stays empty even with evictions on
        cache = self._cache()
        qp = plane_factory(cache)
        _run(cache)
        resp = _probe(qp, {"queue": "default", "count": 1,
                           "requests": {"cpu": 1000, "memory": GiB},
                           "evictions": True})
        assert resp["unmodeled"] == []

    def test_drf_victim_gate_reported_on_eviction_probes_only(
        self, plane_factory
    ):
        cache = self._cache()
        qp = plane_factory(cache)
        self._run_conf(cache, self.DRF_TIER1_CONF)
        lease = qp.broker.current()
        assert lease.unmodeled_gates == ("drf",)
        with_ev = _probe(qp, {"queue": "default", "count": 1,
                              "requests": {"cpu": 1000, "memory": GiB},
                              "evictions": True})
        assert any("drf" in gap for gap in with_ev["unmodeled"])
        plain = _probe(qp, {"queue": "default", "count": 1,
                            "requests": {"cpu": 1000, "memory": GiB}})
        # the gate only affects eviction answers — plain probes stay clean
        assert plain["unmodeled"] == []

    def test_all_best_effort_gang_reports_backfill_gap(self, plane_factory):
        cache = self._cache()
        qp = plane_factory(cache)
        _run(cache)
        resp = _probe(qp, {"queue": "default", "count": 2, "requests": {}})
        assert resp["feasible"] is False  # documented probe scope
        assert any("backfill" in gap.lower() for gap in resp["unmodeled"])

    def test_cli_render_surfaces_unmodeled(self):
        from kube_batch_tpu.cli.whatif import _render

        out = _render({
            "feasible": False, "snapshot_version": 7, "nodes": [None],
            "unmodeled": ["preempt victim gate 'drf' (conf tier) is not "
                          "modeled by the eviction probe"],
        })
        assert "! unmodeled:" in out and "drf" in out
