"""Scheduler-conf loader tests (the rebuild's analog of the conf parsing
covered by pkg/scheduler/util.go:44-70 + framework/arguments_test.go)."""

import pytest

from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.framework.arguments import Arguments
from kube_batch_tpu.framework.conf import (
    DEFAULT_CONF,
    load_scheduler_conf,
    parse_scheduler_conf,
)


class TestConfParsing:
    def test_default_conf(self):
        """Built-in fallback (util.go:31-42): allocate+backfill, two tiers."""
        conf = load_scheduler_conf(None)
        assert conf.actions == ["allocate", "backfill"]
        assert len(conf.tiers) == 2
        tier1 = [p.name for p in conf.tiers[0].plugins]
        assert tier1 == ["priority", "gang", "conformance"]

    def test_shipped_conf_shape(self):
        """The shipped kube-batch-conf.yaml uses all five actions."""
        conf = parse_scheduler_conf(
            'actions: "enqueue, reclaim, allocate, backfill, preempt"\n'
            "tiers:\n- plugins:\n  - name: gang\n"
        )
        assert conf.actions == ["enqueue", "reclaim", "allocate", "backfill", "preempt"]

    def test_enable_switches_parse(self):
        conf = parse_scheduler_conf(
            "actions: allocate\n"
            "tiers:\n"
            "- plugins:\n"
            "  - name: drf\n"
            "    enabledPreemptable: false\n"
        )
        opt = conf.tiers[0].plugins[0]
        assert opt.enabled_preemptable is False
        assert opt.enabled_job_order is True  # defaults true (defaults.go:22-52)

    def test_arguments_passed_through(self):
        conf = parse_scheduler_conf(
            "actions: allocate\n"
            "tiers:\n"
            "- plugins:\n"
            "  - name: nodeorder\n"
            "    arguments:\n"
            "      leastrequested.weight: 2\n"
        )
        args = conf.tiers[0].plugins[0].arguments
        assert args.get_int("leastrequested.weight", 1) == 2

    def test_conf_file_roundtrip(self, tmp_path):
        p = tmp_path / "conf.yaml"
        p.write_text(DEFAULT_CONF)
        conf = load_scheduler_conf(str(p))
        assert conf.actions == ["allocate", "backfill"]


class TestArguments:
    """arguments_test.go:24-76 GetInt table."""

    def test_get_int(self):
        args = Arguments({"k": "5"})
        assert args.get_int("k", 1) == 5
        assert args.get_int("missing", 7) == 7

    def test_get_int_garbage_falls_back(self):
        args = Arguments({"k": "not-a-number"})
        assert args.get_int("k", 3) == 3

    def test_get_bool(self):
        args = Arguments({"t": "true", "f": "false"})
        assert args.get_bool("t", False) is True
        assert args.get_bool("f", True) is False
        assert args.get_bool("missing", True) is True

    def test_get_float(self):
        args = Arguments({"w": "1.5"})
        assert args.get_float("w", 1.0) == 1.5


class TestConfHotReload:
    """The reference's stated-but-unimplemented hot-reload design
    (doc/design/plugin-conf.md; its code re-reads only at startup,
    scheduler.go:70-83): a changed, valid conf swaps in at the cycle
    boundary; a broken edit keeps the running configuration."""

    def _write(self, path, actions):
        path.write_text(f'actions: "{actions}"\ntiers:\n- plugins:\n  - name: gang\n')

    def test_valid_edit_swaps_in(self, tmp_path):
        from kube_batch_tpu.cache.cache import SchedulerCache
        from kube_batch_tpu.scheduler import Scheduler
        import os
        import time

        conf = tmp_path / "conf.yaml"
        self._write(conf, "allocate")
        sched = Scheduler(SchedulerCache(), conf_path=str(conf))
        assert sched.conf.actions == ["allocate"]
        sched.run_once()
        self._write(conf, "allocate, backfill")
        os.utime(conf, (time.time() + 2, time.time() + 2))  # force mtime step
        sched.run_once()
        assert sched.conf.actions == ["allocate", "backfill"]

    def test_broken_edit_keeps_running_conf(self, tmp_path):
        from kube_batch_tpu.cache.cache import SchedulerCache
        from kube_batch_tpu.scheduler import Scheduler
        import os
        import time

        conf = tmp_path / "conf.yaml"
        self._write(conf, "allocate")
        sched = Scheduler(SchedulerCache(), conf_path=str(conf))
        sched.run_once()
        conf.write_text('actions: "no-such-action"\n')
        os.utime(conf, (time.time() + 2, time.time() + 2))
        sched.run_once()  # must not raise
        assert sched.conf.actions == ["allocate"]

    def test_explicit_conf_object_never_reloads(self, tmp_path):
        from kube_batch_tpu.cache.cache import SchedulerCache
        from kube_batch_tpu.framework.conf import load_scheduler_conf
        from kube_batch_tpu.scheduler import Scheduler

        sched = Scheduler(SchedulerCache(), conf=load_scheduler_conf(None))
        assert sched._conf_path is None
        sched.run_once()  # no file to watch; no-op reload path

    def test_unknown_plugin_edit_keeps_running_conf(self, tmp_path):
        from kube_batch_tpu.cache.cache import SchedulerCache
        from kube_batch_tpu.scheduler import Scheduler
        import os
        import time

        conf = tmp_path / "conf.yaml"
        self._write(conf, "allocate")
        sched = Scheduler(SchedulerCache(), conf_path=str(conf))
        sched.run_once()
        # valid actions, typo'd plugin: must be rejected at reload time, not
        # crash every later open_session
        conf.write_text('actions: "allocate"\ntiers:\n- plugins:\n  - name: gangg\n')
        os.utime(conf, (time.time() + 2, time.time() + 2))
        sched.run_once()
        assert sched.conf.tiers[0].plugins[0].name == "gang"
        sched.run_once()  # still scheduling with the running conf
