"""Sharded-resident solve path: bit-exact equivalence over randomized churn.

The conftest forces an 8-device virtual CPU mesh, so the mesh-sharded solve
(and its per-shard scatter-delta residency, api/resident.py) runs in-process
here: a ≥200-node cluster pads past SHARD_MIN_NODES and the allocate action
dispatches sharded.  These tests churn a real cache through real cycles and
assert the acceptance criteria of the sharded-residency PR:

- the sharded-delta device columns fetch back bit-identical to the host
  columns every cycle (the scatter writes exactly the changed rows);
- sharded-delta vs sharded-full-upload (KB_DEVICE_CACHE=0) vs single-device
  (KB_SHARD=0) cycles produce identical binds and end state;
- a mesh change / device-count change falls back to a full re-upload.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.framework.interface import get_action
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu.testing.synthetic import synthetic_cluster

N_NODES = 200   # pads to 256 == SHARD_MIN_NODES → the sharded path engages
N_TASKS = 240


def _mk_cache(seed=0):
    return synthetic_cluster(
        n_tasks=N_TASKS, n_nodes=N_NODES, gang_size=4, n_queues=2, seed=seed
    )


def _churn(cache, rng, serial):
    """Seed-deterministic churn: complete one bound gang, add one gang."""
    from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod, PodGroup
    from kube_batch_tpu.api.types import PodPhase

    for uid, job in sorted(cache.jobs.items()):
        pods = [cache.pods.get(key) for key in sorted(job.tasks)]
        if pods and all(p is not None and p.node_name for p in pods):
            for p in pods:
                cache.delete_pod(p)
            cache.delete_pod_group(uid)
            break
    j = next(serial)
    cache.add_pod_group(PodGroup(
        name=f"sh{j}", namespace="shard", min_member=2,
        queue=f"q{j % 2}", creation_index=10_000 + j,
    ))
    for t in range(2):
        cache.add_pod(Pod(
            name=f"sh{j}-{t}", namespace="shard",
            requests={"cpu": float(rng.choice([250.0, 500.0])),
                      "memory": float(2 ** 30)},
            annotations={GROUP_NAME_ANNOTATION: f"sh{j}"},
            phase=PodPhase.PENDING,
            creation_index=(10_000 + j) * 10 + t,
        ))


def _run_cycles(cache, conf, cycles=5, seed=7):
    """Run `cycles` churned scheduling cycles; returns the per-cycle bind
    sequences and the final task-status column."""
    import itertools

    rng = np.random.default_rng(seed)
    serial = itertools.count(1)
    binds = []
    for _ in range(cycles):
        _churn(cache, rng, serial)
        ssn = open_session(cache, conf.tiers)
        try:
            for name in conf.actions:
                get_action(name).execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()
        binds.append(sorted(cache.binder.binds.items()))
    cols = cache.columns
    status = [
        (cols.task_by_row[r]._key, int(cols.t_status[r]))
        for r in np.flatnonzero(cols.t_valid).tolist()
    ]
    return binds, sorted(status)


@pytest.fixture
def _env_guard():
    saved = {k: os.environ.get(k) for k in ("KB_DEVICE_CACHE", "KB_SHARD")}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def test_allocate_dispatches_sharded_with_resident_cache():
    """The sharded dispatch must ride the per-shard scatter cache: after a
    few churn cycles the sharded cache exists, scatter-delta updates
    engaged, and every cached field round-trips bit-exact."""
    from kube_batch_tpu.api.columns import resident_snap
    from kube_batch_tpu.api.resident import PER_CYCLE_FIELDS
    from kube_batch_tpu.parallel.mesh import default_mesh

    import itertools

    cache = _mk_cache()
    conf = load_scheduler_conf(None)
    rng = np.random.default_rng(3)
    serial = itertools.count(1)
    cols = cache.columns
    mesh = default_mesh()
    assert mesh is not None, "conftest must provide the 8-device mesh"
    for cycle in range(5):
        _churn(cache, rng, serial)
        ssn = open_session(cache, conf.tiers)
        try:
            snap, _meta = cols.device_snapshot(ssn)
            swapped = resident_snap(cols, snap, mesh)
            for field in PER_CYCLE_FIELDS:
                host = np.asarray(getattr(snap, field))
                dev = np.asarray(getattr(swapped, field))
                assert np.array_equal(host, dev), (
                    f"cycle {cycle}: sharded-resident {field} diverged"
                )
            for name in conf.actions:
                get_action(name).execute(ssn)
            assert get_action("allocate").last_solve_mode == "sharded"
        finally:
            close_session(ssn)
        cache.flush_binds()
    sharded = cols._per_cycle_dev.get(mesh)
    assert sharded is not None
    assert sharded.scatter_updates > 0, "per-shard delta path never engaged"
    assert sharded.clean_hits > 0
    assert cols.check_consistency(cache) == []


def test_sharded_delta_vs_full_vs_single_bit_exact(_env_guard):
    """Identical churn on three caches — sharded+delta, sharded with the
    resident cache disabled (full uploads), and the single-device solve —
    must produce identical bind sequences and end state."""
    conf = load_scheduler_conf(None)

    os.environ.pop("KB_DEVICE_CACHE", None)
    os.environ.pop("KB_SHARD", None)
    binds_delta, status_delta = _run_cycles(_mk_cache(), conf)

    os.environ["KB_DEVICE_CACHE"] = "0"
    binds_full, status_full = _run_cycles(_mk_cache(), conf)
    os.environ.pop("KB_DEVICE_CACHE", None)

    os.environ["KB_SHARD"] = "0"
    binds_single, status_single = _run_cycles(_mk_cache(), conf)
    os.environ.pop("KB_SHARD", None)

    assert binds_delta == binds_full, "sharded delta vs full binds diverged"
    assert status_delta == status_full
    assert binds_delta == binds_single, "sharded vs single binds diverged"
    assert status_delta == status_single


def test_mesh_change_falls_back_to_full_upload():
    """A mesh change (reshard / device-set change) must drop the old
    sharded cache wholesale and full-upload once on the new mesh."""
    from kube_batch_tpu.api.columns import resident_snap
    from kube_batch_tpu.parallel.mesh import make_mesh

    cache = _mk_cache()
    conf = load_scheduler_conf(None)
    cols = cache.columns
    ssn = open_session(cache, conf.tiers)
    try:
        snap, _meta = cols.device_snapshot(ssn)
        mesh8 = make_mesh(8)
        resident_snap(cols, snap, mesh8)
        c8 = cols._per_cycle_dev.get(mesh8)
        assert c8 is not None and c8.full_uploads > 0
        # reshard to a 4-device mesh: the 8-device cache must be dropped
        mesh4 = make_mesh(4)
        swapped = resident_snap(cols, snap, mesh4)
        assert cols._per_cycle_dev.get(mesh8) is None
        c4 = cols._per_cycle_dev.get(mesh4)
        assert c4 is not None and c4.full_uploads > 0
        host = np.asarray(snap.node_idle)
        assert np.array_equal(host, np.asarray(swapped.node_idle))
    finally:
        close_session(ssn)


def test_high_churn_delta_falls_back_to_full_upload(monkeypatch):
    """A per-shard delta wider than the slot budget re-uploads the whole
    (sharded) column — values stay exact either way."""
    from kube_batch_tpu.api import resident as res
    from kube_batch_tpu.parallel.mesh import make_mesh

    # shrink the per-shard budget so a 16-row single-shard delta overflows
    monkeypatch.setattr(res, "SHARD_SCATTER_SLOTS", 8)
    cache = _mk_cache()
    conf = load_scheduler_conf(None)
    cols = cache.columns
    ssn = open_session(cache, conf.tiers)
    try:
        snap, _meta = cols.device_snapshot(ssn)
        c = res.ShardedPerCycleDeviceCache(make_mesh(8))
        c.swap(snap)
        uploads0, scatters0 = c.full_uploads, c.scatter_updates
        # 16 changed rows land in shard 0 (shard size 32) — over budget
        host = np.asarray(snap.node_idle)
        wide = host.copy()
        wide[:16] += 1.0
        snap2 = snap._replace(node_idle=wide)
        swapped = c.swap(snap2)
        assert np.array_equal(wide, np.asarray(swapped.node_idle))
        assert c.full_uploads > uploads0, "wide delta must full-upload"
        # a later small delta rides the scatter again
        wide2 = wide.copy()
        wide2[3] += 1.0
        swapped = c.swap(snap2._replace(node_idle=wide2))
        assert np.array_equal(wide2, np.asarray(swapped.node_idle))
        assert c.scatter_updates > scatters0
    finally:
        close_session(ssn)
