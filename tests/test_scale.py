"""CPU scale test of the allocate cycle at 5k tasks × 500 nodes.

Asserts the *invariants* (SURVEY.md §7.3 — the reference randomizes
placement itself, scheduler_helper.go:147-158): no node overcommit, no
committed partial gang, overused queues gain nothing — at a size that
crosses the 4096→8192 task padding-bucket boundary (api/snapshot.py
power-of-two buckets), which the unit tests (≤512 tasks) never exercise.
"""

from __future__ import annotations

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.cluster_info import ClusterInfo
from kube_batch_tpu.api.snapshot import build_snapshot
from kube_batch_tpu.api.types import TaskStatus, is_allocated
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.framework.interface import get_action
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu.testing.synthetic import (
    synthetic_cluster,
    synthetic_overcommit_cluster,
)

GANG = 5
N_TASKS = 5000
N_NODES = 500


def _session_view(ssn):
    cluster = ClusterInfo(ssn.spec)
    cluster.nodes = ssn.nodes
    cluster.queues = ssn.queues
    cluster.jobs = ssn.jobs
    return cluster


@pytest.mark.slow
def test_allocate_invariants_at_scale():
    cache = synthetic_cluster(
        n_tasks=N_TASKS, n_nodes=N_NODES, gang_size=GANG, n_queues=3
    )
    conf = load_scheduler_conf(None)
    ssn = open_session(cache, conf.tiers)

    # the padded task axis must cross the 4096 bucket boundary: 5000 tasks
    # land in the next power-of-two bucket, and the padding rows must not
    # perturb the solve below
    snap, meta = build_snapshot(_session_view(ssn))
    assert meta.n_tasks == N_TASKS
    padded_T = snap.task_req.shape[0]
    assert padded_T > 4096 and padded_T >= N_TASKS

    get_action("allocate").execute(ssn)

    # 1. no node overcommit, in the authoritative host accounting
    quanta = ssn.spec.quanta
    placed = 0
    for node in ssn.nodes.values():
        assert np.all(node.idle.vec >= -quanta), node.name
        assert np.all(
            node.used.vec <= node.allocatable.vec + quanta
        ), node.name
        placed += sum(
            1 for t in node.tasks.values() if is_allocated(t.status)
        )

    # 2. no committed partial gang: every job placed all-or-nothing
    for job in ssn.jobs.values():
        n_alloc = sum(
            1 for t in job.tasks.values() if is_allocated(t.status)
        )
        assert n_alloc == 0 or n_alloc >= job.min_available, job.uid

    # 3. the solve actually did the work (not a vacuous pass): the synthetic
    # cluster is sized so most tasks fit
    assert placed >= N_TASKS // 2
    close_session(ssn)

    # 4. the persistent column store stayed consistent through a columnar
    # replay that crossed the 4096 task bucket (axis growth + vectorized
    # apply + close unwind)
    errs = cache.columns.check_consistency(cache)
    assert not errs, errs[:5]


@pytest.mark.slow
def test_eviction_invariants_at_scale():
    """reclaim/preempt across the 4096 task bucket (VERDICT r3 #3): the
    per-claimant queue-capacity gather (one-hot matmul over the queue axis,
    ops/eviction.py) must preserve the eviction invariants the reference
    enforces serially — cross-queue victims only (reclaim.go:134-147),
    eviction only alongside a covered pipelined claim (reclaim.go:150-163),
    and no node overcommit in the authoritative host accounting."""
    cache = synthetic_overcommit_cluster(
        n_running=2048, n_pending=2600, n_nodes=256, gang_size=4
    )
    conf = load_scheduler_conf(None)
    conf.actions = ["enqueue", "reclaim", "allocate", "backfill", "preempt"]
    ssn = open_session(cache, conf.tiers)

    snap, _meta = build_snapshot(_session_view(ssn))
    assert snap.task_req.shape[0] > 4096  # 4648 tasks → 8192 bucket

    for name in conf.actions:
        get_action(name).execute(ssn)

    quanta = ssn.spec.quanta
    for node in ssn.nodes.values():
        assert np.all(node.idle.vec >= -quanta), node.name
        # mid-eviction, Used counts the dying victims (Releasing) alongside
        # the Pipelined claimants placed onto their future resources
        # (node_info.go:165-222 status algebra) — what must not overcommit
        # is the steady state after the releases complete: everything
        # occupying the node then, recomputed from task statuses. Coverage
        # is epsilon-tolerant per claim, so the slack scales with the
        # number of pipelined claimants on the node.
        future = ssn.spec.empty()
        n_pipe = 0
        for t in node.tasks.values():
            if t.status == TaskStatus.RELEASING:
                continue
            future.add_(t.resreq)
            n_pipe += t.status == TaskStatus.PIPELINED
        assert np.all(
            future.vec <= node.allocatable.vec + quanta * (1 + n_pipe)
        ), node.name

    evicted = [
        t for job in ssn.jobs.values() for t in job.tasks.values()
        if t.status == TaskStatus.RELEASING
    ]
    pipelined = [
        t for job in ssn.jobs.values() for t in job.tasks.values()
        if t.status == TaskStatus.PIPELINED
    ]
    # the overcommitted cluster converges toward q1's deserved share: real
    # evictions happened, and work pipelined onto the freed resources
    assert evicted and pipelined
    # reclaim victims come only from the other queue (q0 holds the cluster;
    # the starved claimants are all in q1)
    for t in evicted:
        assert ssn.jobs[t.job].queue == "q0", (t.uid, ssn.jobs[t.job].queue)
    for t in pipelined:
        assert ssn.jobs[t.job].queue == "q1", (t.uid, ssn.jobs[t.job].queue)
    close_session(ssn)

    errs = cache.columns.check_consistency(cache)
    assert not errs, errs[:5]


@pytest.mark.slow
def test_overused_queue_gains_nothing_at_scale():
    """proportion's Overused gate (proportion.go:198-209): a queue whose
    running allocation already exceeds its deserved share gets no new
    placements even with pending work queued."""
    from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod, PodGroup
    from kube_batch_tpu.api.types import PodPhase

    cache = synthetic_overcommit_cluster(
        n_running=800, n_pending=400, n_nodes=100, gang_size=4
    )
    # pending work in the overused queue q0 (weight 1 vs q1's 3; q0 already
    # runs the whole cluster, far beyond its ~25% deserved share)
    for j in range(10):
        cache.add_pod_group(
            PodGroup(name=f"greedy{j}", namespace="bench", min_member=1,
                     queue="q0", creation_index=10_000 + j)
        )
        cache.add_pod(
            Pod(
                name=f"g{j}", namespace="bench",
                requests={"cpu": 100.0, "memory": float(2 ** 28)},
                annotations={GROUP_NAME_ANNOTATION: f"greedy{j}"},
                phase=PodPhase.PENDING,
                creation_index=10_000 + j,
            )
        )
    conf = load_scheduler_conf(None)
    ssn = open_session(cache, conf.tiers)
    get_action("allocate").execute(ssn)
    for uid, job in ssn.jobs.items():
        if not uid.startswith("bench/greedy"):
            continue
        for t in job.tasks.values():
            assert t.status == TaskStatus.PENDING, (uid, t.status)
    close_session(ssn)


@pytest.mark.slow
def test_eviction_many_queues_bucket():
    """The per-claimant queue-capacity gather at a queue bucket > 8 (the
    one-hot matmul's contraction axis, ops/eviction.py): 12 queues land in
    the 16-wide bucket; claimants across 11 starved queues must reclaim only
    cross-queue victims and never their own queue's capacity."""
    from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod, PodGroup, Queue
    from kube_batch_tpu.api.types import PodPhase
    from kube_batch_tpu.cache.cache import SchedulerCache
    from kube_batch_tpu.testing.synthetic import GiB as _GiB

    cache = SchedulerCache()
    cache.add_queue(Queue(name="q0", weight=1))
    for i in range(1, 12):
        cache.add_queue(Queue(name=f"q{i}", weight=2))
    from kube_batch_tpu.api.pod import Node

    for n in range(8):
        cache.add_node(Node(name=f"n{n}", allocatable={
            "cpu": 8000.0, "memory": float(64 * _GiB), "pods": 110.0}))
    # q0 saturates every node's cpu with 8 x 1000m per node
    for i in range(64):
        cache.add_pod_group(PodGroup(name=f"r{i}", namespace="b", min_member=1,
                                     queue="q0", creation_index=i))
        cache.add_pod(Pod(
            name=f"r{i}", namespace="b", requests={"cpu": 1000.0, "memory": float(_GiB)},
            annotations={GROUP_NAME_ANNOTATION: f"r{i}"},
            phase=PodPhase.RUNNING, node_name=f"n{i % 8}", creation_index=i,
        ))
    # one pending claimant per starved queue
    for i in range(1, 12):
        cache.add_pod_group(PodGroup(name=f"p{i}", namespace="b", min_member=1,
                                     queue=f"q{i}", creation_index=100 + i))
        cache.add_pod(Pod(
            name=f"p{i}", namespace="b", requests={"cpu": 1000.0, "memory": float(_GiB)},
            annotations={GROUP_NAME_ANNOTATION: f"p{i}"},
            phase=PodPhase.PENDING, creation_index=100 + i,
        ))
    conf = load_scheduler_conf(None)
    ssn = open_session(cache, conf.tiers)
    get_action("reclaim").execute(ssn)
    evicted = [t for job in ssn.jobs.values() for t in job.tasks.values()
               if t.status == TaskStatus.RELEASING]
    pipelined = [t for job in ssn.jobs.values() for t in job.tasks.values()
                 if t.status == TaskStatus.PIPELINED]
    assert evicted and pipelined
    assert all(ssn.jobs[t.job].queue == "q0" for t in evicted)
    assert all(ssn.jobs[t.job].queue != "q0" for t in pipelined)
    # most starved queues get their claim in one cycle (8 nodes → up to 8
    # claims per round; rounds continue while progress is made)
    assert len(pipelined) >= 8, len(pipelined)
    close_session(ssn)
    assert not cache.columns.check_consistency(cache)

    # no-churn convergence (the idle-fit claimant gate, a declared
    # improvement over reclaim.go): once the victims terminate, claimants
    # fit free capacity, so the next cycles allocate WITHOUT new evictions
    for key in list(cache.evictor.evicts):
        pod = cache.pods.get(key)
        if pod is not None:
            cache.delete_pod(pod)
    cache.evictor.evicts.clear()
    conf2 = load_scheduler_conf(None)
    conf2.actions = ["enqueue", "reclaim", "allocate", "backfill", "preempt"]
    ssn2 = open_session(cache, conf2.tiers)
    # the idle-fit gate fails closed without pipeline info — publish it the
    # way Scheduler.run_once does
    ssn2.action_names = list(conf2.actions)
    for name in conf2.actions:
        get_action(name).execute(ssn2)
    close_session(ssn2)
    cache.flush_binds()
    assert not cache.evictor.evicts, cache.evictor.evicts
    bound = sum(1 for k in cache.binder.binds if k.startswith("b/p"))
    assert bound >= len(pipelined), (bound, len(pipelined))


@pytest.mark.slow
def test_idle_gate_off_without_allocate_after_reclaim():
    """The idle-fit claimant gate must disable itself when the configured
    pipeline has no allocate after reclaim — otherwise a skipped claimant
    would never be scheduled at all (strictly worse than the reference)."""
    from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup, Queue
    from kube_batch_tpu.api.types import PodPhase
    from kube_batch_tpu.cache.cache import SchedulerCache
    from kube_batch_tpu.scheduler import Scheduler

    GiB = float(2 ** 30)
    cache = SchedulerCache()
    cache.add_queue(Queue(name="q0", weight=1))
    cache.add_queue(Queue(name="q1", weight=3))
    # node with FREE cpu (claimant fits idle) AND a cross-queue victim
    cache.add_node(Node(name="n1", allocatable={
        "cpu": 4000.0, "memory": float(64 * GiB), "pods": 110.0}))
    cache.add_pod_group(PodGroup(name="r", namespace="b", min_member=1,
                                 queue="q0", creation_index=0))
    cache.add_pod(Pod(name="r", namespace="b",
                      requests={"cpu": 1000.0, "memory": GiB},
                      annotations={GROUP_NAME_ANNOTATION: "r"},
                      phase=PodPhase.RUNNING, node_name="n1",
                      creation_index=0))
    cache.add_pod_group(PodGroup(name="p", namespace="b", min_member=1,
                                 queue="q1", creation_index=1))
    cache.add_pod(Pod(name="p", namespace="b",
                      requests={"cpu": 1000.0, "memory": GiB},
                      annotations={GROUP_NAME_ANNOTATION: "p"},
                      phase=PodPhase.PENDING, creation_index=1))
    conf = load_scheduler_conf(None)
    conf.actions = ["reclaim"]  # no allocate at all
    sched = Scheduler(cache, conf=conf)
    sched.run_once()
    # without the gate disabling itself, the fitting claimant would be
    # masked out and NOTHING would happen; with it off (no allocate in the
    # pipeline), reclaim behaves like the reference: the victim is evicted
    # (the pipeline itself is session-only state, reverted at close)
    assert "b/r" in cache.evictor.evicts, cache.evictor.evicts
