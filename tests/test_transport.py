"""Classified retry/backoff transport + circuit breaker (k8s/transport.py):
the error-classification table, Retry-After honoring, decorrelated-jitter
bounds, per-endpoint-class budgets, breaker state transitions, and the
K8sBackend idempotency satellites (bind 409, evict 404) — all against
stubbed openers/clocks, no network."""

import io
import random
import socket
import ssl
import urllib.error

import pytest

from kube_batch_tpu.k8s.transport import (
    FATAL,
    THROTTLE,
    TRANSIENT,
    ApiTransport,
    Backoff,
    CircuitBreaker,
    CircuitOpenError,
    GuardedBackend,
    RetryPolicy,
    classify_error,
)


def http_error(code: int, headers=None) -> urllib.error.HTTPError:
    return urllib.error.HTTPError("http://api", code, "x", headers or {},
                                  io.BytesIO())


class TestClassification:
    @pytest.mark.parametrize("exc,kind", [
        (http_error(429), THROTTLE),
        (http_error(503), THROTTLE),
        (http_error(408), TRANSIENT),
        (http_error(500), TRANSIENT),
        (http_error(502), TRANSIENT),
        (http_error(504), TRANSIENT),
        (http_error(501), FATAL),      # Not Implemented: retrying is noise
        (http_error(400), FATAL),
        (http_error(404), FATAL),
        (http_error(409), FATAL),
        (http_error(403), FATAL),
        (ConnectionRefusedError(), TRANSIENT),
        (ConnectionResetError(), TRANSIENT),
        (socket.timeout(), TRANSIENT),
        (TimeoutError(), TRANSIENT),
        (OSError("unreachable"), TRANSIENT),
        (urllib.error.URLError(ConnectionRefusedError()), TRANSIENT),
        (urllib.error.URLError("bad"), TRANSIENT),
        (ssl.SSLError(), TRANSIENT),
        (ValueError("bug"), FATAL),    # unknown program errors don't retry
    ])
    def test_table(self, exc, kind):
        assert classify_error(exc)[0] == kind

    def test_mid_response_drops_are_transient(self):
        """A connection cut mid-body surfaces as http.client exceptions or
        a truncated-JSON decode error, none of which are OSErrors — they
        must retry (and count as breaker failures), not classify fatal."""
        import http.client
        import json as _json

        assert classify_error(http.client.IncompleteRead(b"x"))[0] == TRANSIENT
        assert classify_error(http.client.BadStatusLine(""))[0] == TRANSIENT
        try:
            _json.loads("{trunc")
        except _json.JSONDecodeError as e:
            assert classify_error(e)[0] == TRANSIENT

    def test_cert_verification_failure_is_fatal(self):
        try:
            err = ssl.SSLCertVerificationError("bad cert")
        except AttributeError:  # pragma: no cover — very old ssl
            pytest.skip("no SSLCertVerificationError")
        assert classify_error(err)[0] == FATAL
        # also when wrapped in a URLError, as urlopen delivers it
        assert classify_error(urllib.error.URLError(err))[0] == FATAL

    def test_retry_after_seconds_parsed(self):
        kind, after = classify_error(http_error(429, {"Retry-After": "7"}))
        assert (kind, after) == (THROTTLE, 7.0)

    def test_retry_after_http_date_falls_back_to_backoff(self):
        kind, after = classify_error(
            http_error(503, {"Retry-After": "Wed, 21 Oct 2026 07:28:00 GMT"}))
        assert kind == THROTTLE and after is None


class TestBackoffAndPolicy:
    def test_decorrelated_jitter_bounds(self):
        bo = Backoff(base=0.5, cap=30.0, rng=random.Random(7))
        prev = 0.5
        for _ in range(200):
            d = bo.next()
            assert 0.5 <= d <= min(30.0, prev * 3.0) + 1e-9
            prev = max(0.5, d)

    def test_backoff_caps(self):
        bo = Backoff(base=1.0, cap=4.0, rng=random.Random(1))
        for _ in range(50):
            assert bo.next() <= 4.0

    def test_reset_restarts_the_ramp(self):
        bo = Backoff(base=1.0, cap=100.0, rng=random.Random(3))
        for _ in range(10):
            bo.next()
        bo.reset()
        assert bo.next() <= 3.0  # first post-reset draw ≤ base*3

    def test_budgets_per_endpoint_class(self):
        p = RetryPolicy(budgets={"write": 2})
        assert p.budget("write") == 2
        assert p.budget("read") == 5       # default
        assert p.budget("watch") == 1      # the watch loop is the retry
        assert p.budget("unknown") == p.budget("read")

    def test_throttle_delay_honors_retry_after_capped(self):
        p = RetryPolicy(base=0.1, cap=5.0, rng=random.Random(0))
        bo = p.backoff_state()
        assert p.delay(THROTTLE, 3.0, bo) == 3.0
        assert p.delay(THROTTLE, 500.0, bo) == 5.0  # hostile header capped
        # no header → ordinary jittered backoff
        assert 0.1 <= p.delay(THROTTLE, None, bo) <= 5.0


def make_transport(**kw) -> ApiTransport:
    t = ApiTransport(
        "http://api", retry_policy=kw.pop("retry_policy", None)
        or RetryPolicy(base=0.01, cap=0.05, rng=random.Random(0)),
        breaker=kw.pop("breaker", None)
        or CircuitBreaker(threshold=3, cooldown=10.0, clock=lambda: 0.0),
    )
    t.slept = []
    t._sleep = t.slept.append
    return t


class TestCallRetryLoop:
    def test_transient_retries_then_succeeds(self):
        t = make_transport()
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionRefusedError()
            return "ok"

        assert t._call("read", fn) == "ok"
        assert len(calls) == 3 and len(t.slept) == 2
        assert t.breaker.state == "closed"

    def test_budget_exhaustion_raises_the_last_error(self):
        t = make_transport(breaker=CircuitBreaker(
            threshold=10, cooldown=10.0, clock=lambda: 0.0))

        def fn():
            raise ConnectionResetError("still down")

        with pytest.raises(ConnectionResetError):
            t._call("write", fn)
        # write budget = 4 attempts → 3 sleeps
        assert len(t.slept) == 3

    def test_fatal_is_raised_immediately_and_spares_the_breaker(self):
        t = make_transport()
        calls = []

        def fn():
            calls.append(1)
            raise http_error(404)

        with pytest.raises(urllib.error.HTTPError):
            t._call("read", fn)
        assert len(calls) == 1 and t.slept == []
        # a 4xx means the server is healthy: consecutive-failure count reset
        assert t.breaker.state == "closed"

    def test_retry_after_shapes_the_sleep(self):
        t = make_transport(retry_policy=RetryPolicy(
            base=0.01, cap=30.0, rng=random.Random(0)))
        calls = []

        def fn():
            calls.append(1)
            if len(calls) == 1:
                raise http_error(429, {"Retry-After": "2"})
            return "ok"

        assert t._call("read", fn) == "ok"
        assert t.slept == [2.0]

    def test_retry_false_makes_one_attempt(self):
        t = make_transport()
        calls = []

        def fn():
            calls.append(1)
            raise ConnectionRefusedError()

        with pytest.raises(ConnectionRefusedError):
            t._call("read", fn, retry=False)
        assert len(calls) == 1 and t.slept == []

    def test_open_breaker_fails_fast(self):
        clock = [0.0]
        t = make_transport(breaker=CircuitBreaker(
            threshold=1, cooldown=10.0, clock=lambda: clock[0]))

        def fn():
            raise ConnectionRefusedError()

        with pytest.raises(ConnectionRefusedError):
            t._call("read", fn, retry=False)
        assert t.breaker.state == "open"
        with pytest.raises(CircuitOpenError):
            t._call("read", lambda: "never runs")


class TestCircuitBreaker:
    def test_closed_to_open_after_threshold(self):
        b = CircuitBreaker(threshold=3, cooldown=5.0, clock=lambda: 0.0,
                           name="t")
        for _ in range(2):
            b.record_failure()
        assert b.state == "closed"
        b.record_failure()
        assert b.state == "open" and b.is_open

    def test_success_resets_the_consecutive_count(self):
        b = CircuitBreaker(threshold=2, cooldown=5.0, clock=lambda: 0.0)
        b.record_failure()
        b.record_success()
        b.record_failure()
        assert b.state == "closed"

    def test_half_open_admits_one_probe_then_closes_on_success(self):
        clock = [0.0]
        b = CircuitBreaker(threshold=1, cooldown=5.0, clock=lambda: clock[0],
                           name="t2")
        b.record_failure()
        assert not b.allow()            # open, cooldown running
        clock[0] = 6.0
        assert b.allow()                # half-open: the single probe
        assert not b.allow()            # second caller refused mid-probe
        b.record_success()
        assert b.state == "closed" and b.allow()

    def test_half_open_failure_reopens_with_fresh_cooldown(self):
        clock = [0.0]
        b = CircuitBreaker(threshold=1, cooldown=5.0, clock=lambda: clock[0])
        b.record_failure()
        clock[0] = 6.0
        assert b.allow()                # probe
        b.record_failure()
        assert b.state == "open"
        clock[0] = 10.0                 # 4s into the NEW cooldown
        assert not b.allow()
        clock[0] = 11.5
        assert b.allow()                # next probe window

    def test_transition_counters(self):
        clock = [0.0]
        b = CircuitBreaker(threshold=1, cooldown=1.0, clock=lambda: clock[0])
        b.record_failure()
        clock[0] = 2.0
        b.allow()
        b.record_success()
        assert b.transitions["open"] == 1
        assert b.transitions["half-open"] == 1
        assert b.transitions["closed"] == 1


class _RecordingBackend:
    def __init__(self, fail=0):
        self.fail = fail
        self.binds = []
        self.evicts = []

    def bind(self, pod, hostname):
        if self.fail > 0:
            self.fail -= 1
            raise RuntimeError("down")
        self.binds.append((pod, hostname))

    def bind_many(self, pairs):
        if self.fail > 0:
            self.fail -= 1
            raise RuntimeError("down")
        self.binds.extend(pairs)

    def evict(self, pod):
        self.evicts.append(pod)


class TestGuardedBackend:
    def test_failures_open_then_calls_fail_fast(self):
        clock = [0.0]
        backend = _RecordingBackend(fail=2)
        g = GuardedBackend(backend, CircuitBreaker(
            threshold=2, cooldown=5.0, clock=lambda: clock[0], name="g"))
        for _ in range(2):
            with pytest.raises(RuntimeError):
                g.bind("p", "n")
        assert g.degraded()
        with pytest.raises(CircuitOpenError):
            g.bind("p", "n")
        assert backend.binds == []
        clock[0] = 6.0                  # half-open probe goes through
        g.bind("p", "n")
        assert backend.binds == [("p", "n")] and not g.degraded()

    def test_bind_many_capability_mirrors_the_backend(self):
        class NoBatch:
            def bind(self, pod, hostname):
                pass

            def evict(self, pod):
                pass

        g = GuardedBackend(NoBatch(), CircuitBreaker(clock=lambda: 0.0))
        assert g.bind_many is None      # cache's capability probe sees none
        g2 = GuardedBackend(_RecordingBackend(),
                            CircuitBreaker(clock=lambda: 0.0))
        g2.bind_many([("p", "n")])


class _StubTransport:
    """Raises the queued errors in order, then records the call."""

    def __init__(self, errors=()):
        self.errors = list(errors)
        self.calls = []

    def request(self, method, path, body=None, **kw):
        if self.errors:
            raise self.errors.pop(0)
        self.calls.append((method, path))

    def degraded(self):
        return False


class TestK8sBackendIdempotency:
    def _backend(self, errors=()):
        from kube_batch_tpu.k8s.bind import K8sBackend

        b = K8sBackend("http://api")
        b.transport = _StubTransport(errors)
        return b

    def test_bind_409_is_idempotent_success(self):
        from kube_batch_tpu.api.pod import Pod

        b = self._backend([http_error(409)])
        b.bind(Pod(name="p", namespace="ns", uid="u1"), "n0")  # no raise

    def test_bind_other_http_errors_still_raise(self):
        from kube_batch_tpu.api.pod import Pod

        b = self._backend([http_error(403)])
        with pytest.raises(urllib.error.HTTPError):
            b.bind(Pod(name="p", namespace="ns", uid="u1"), "n0")

    def test_evict_404_still_swallowed(self):
        from kube_batch_tpu.api.pod import Pod

        b = self._backend([http_error(404)])
        b.evict(Pod(name="p", namespace="ns", uid="u1"))  # no raise

    def test_rate_limited_wrapper_forwards_degraded(self):
        """The cache's shed probe must see the wrapped backend's breaker
        through RateLimitedStatusUpdater — the production wiring."""
        from kube_batch_tpu.cmd.server import (
            RateLimitedStatusUpdater,
            TokenBucket,
        )

        class Backend:
            degraded_now = False

            def degraded(self):
                return self.degraded_now

        backend = Backend()
        wrapped = RateLimitedStatusUpdater(backend, bucket=TokenBucket(50, 100))
        assert wrapped.degraded() is False
        backend.degraded_now = True
        assert wrapped.degraded() is True

    def test_per_role_breaker_names(self):
        """Several transports against one host get distinct breaker metric
        labels (writeback vs watch vs lease) — a shared label would be
        last-writer-wins on the open gauge."""
        t1 = ApiTransport("http://api", role="writeback")
        t2 = ApiTransport("http://api", role="watch")
        assert t1.breaker.name != t2.breaker.name
        assert t1.breaker.name.endswith("/writeback")


class TestWatchBackoffSharing:
    def test_reconnect_draws_delays_from_the_shared_policy(self):
        """The per-resource reconnect loop survives seed failures by
        sleeping policy-provided (tiny, test-tuned) delays and proceeds
        once the transport recovers — the private 1→30s doubling is gone."""
        import threading

        from kube_batch_tpu.k8s.watch import WatchAdapter

        w = WatchAdapter.__new__(WatchAdapter)  # transport stubbed below
        w.transport = make_transport()
        w._stream_factory = None
        w._stop = threading.Event()
        seeds = []

        def seed(kind):
            seeds.append(1)
            if len(seeds) < 3:
                raise OSError("apiserver down")
            return "5"

        def watch_events(path):
            w._stop.set()  # one successful watch connect ends the test
            assert "resourceVersion=5" in path
            return iter(())

        w._seed = seed
        w._watch_events = watch_events
        seeded = []
        w._run_resource("pods", on_seeded=lambda: seeded.append(1))
        assert len(seeds) == 3 and seeded == [1]
