"""Top-K candidate compaction (KB_TOPK, ISSUE 10): compacted-vs-full
bit-exactness over randomized churn on the single-device, shard_map, and
pjit paths; the forced-exhaustion fixture proving the full-matrix re-entry
fires and still matches; the exact-lex-top-K extraction against a numpy
reference; zero steady-state retraces on the compacted path; and the
zero-per-round-collective contract of the compacted shard_map program.

The conftest forces an 8-device virtual CPU mesh (like test_shard_map);
clusters in the sharded cases pad past SHARD_MIN_NODES so the allocate
action dispatches sharded.
"""

from __future__ import annotations

import itertools
import os

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.framework.interface import get_action
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu.testing.synthetic import synthetic_cluster

_ENV_KEYS = ("KB_TOPK", "KB_SHARD", "KB_SHARD_MAP", "KB_TASK_SHARDS")


@pytest.fixture
def _env_guard():
    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _churn(cache, rng, serial, namespace="topk"):
    """Seed-deterministic churn: complete one bound gang, add one gang."""
    from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod, PodGroup
    from kube_batch_tpu.api.types import PodPhase

    for uid, job in sorted(cache.jobs.items()):
        pods = [cache.pods.get(key) for key in sorted(job.tasks)]
        if pods and all(p is not None and p.node_name for p in pods):
            for p in pods:
                cache.delete_pod(p)
            cache.delete_pod_group(uid)
            break
    j = next(serial)
    cache.add_pod_group(PodGroup(
        name=f"tk{j}", namespace=namespace, min_member=2,
        queue=f"q{j % 2}", creation_index=30_000 + j,
    ))
    for t in range(2):
        cache.add_pod(Pod(
            name=f"tk{j}-{t}", namespace=namespace,
            requests={"cpu": float(rng.choice([250.0, 500.0, 1000.0])),
                      "memory": float(2 ** 30)},
            annotations={GROUP_NAME_ANNOTATION: f"tk{j}"},
            phase=PodPhase.PENDING,
            creation_index=(30_000 + j) * 10 + t,
        ))


def _run_cycles(cache, conf, cycles=5, seed=11):
    rng = np.random.default_rng(seed)
    serial = itertools.count(1)
    binds = []
    compacted = 0
    for _ in range(cycles):
        _churn(cache, rng, serial)
        ssn = open_session(cache, conf.tiers)
        try:
            for name in conf.actions:
                get_action(name).execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()
        if get_action("allocate").last_topk is not None:
            compacted += 1
        binds.append(sorted(cache.binder.binds.items()))
    cols = cache.columns
    status = sorted(
        (cols.task_by_row[r]._key, int(cols.t_status[r]))
        for r in np.flatnonzero(cols.t_valid).tolist()
    )
    return binds, status, compacted


def _mk_cache(n_tasks=600, n_nodes=48, seed=0):
    # n_tasks pads past the smallest pending bucket (256) so steady churn
    # cycles take the compacted dispatch; the first (cold) cycle's full
    # pending set exceeds the bucket gate and runs the full program
    return synthetic_cluster(
        n_tasks=n_tasks, n_nodes=n_nodes, gang_size=4, n_queues=2, seed=seed
    )


# --------------------------------------------------------------------------
# cycle-level compacted-vs-full equivalence over randomized churn
# --------------------------------------------------------------------------


def test_cycles_topk_vs_full_single_device(_env_guard):
    """Identical churn, KB_TOPK default (compacted) vs KB_TOPK=0 (the
    full-matrix oracle), single-device: binds and end state must be
    identical, and the compacted dispatch must actually engage."""
    conf = load_scheduler_conf(None)
    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    os.environ["KB_SHARD"] = "0"

    binds_t, status_t, compacted = _run_cycles(_mk_cache(), conf)
    assert compacted > 0, "compacted dispatch never engaged"

    os.environ["KB_TOPK"] = "0"
    binds_f, status_f, compacted_f = _run_cycles(_mk_cache(), conf)
    assert compacted_f == 0

    assert binds_t == binds_f, "compacted vs full binds diverged"
    assert status_t == status_f


@pytest.mark.parametrize("impl_env", [{}, {"KB_SHARD_MAP": "0"}])
def test_cycles_topk_sharded_vs_full(_env_guard, impl_env):
    """The sharded compacted path (shard_map default, pjit oracle via
    KB_SHARD_MAP=0) against the full-matrix sharded program under the same
    churn — bit-identical binds and end state."""
    conf = load_scheduler_conf(None)
    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    os.environ.update(impl_env)

    binds_t, status_t, compacted = _run_cycles(
        _mk_cache(n_tasks=600, n_nodes=200), conf)
    assert get_action("allocate").last_solve_mode == "sharded"
    assert compacted > 0, "sharded compacted dispatch never engaged"

    os.environ["KB_TOPK"] = "0"
    binds_f, status_f, _ = _run_cycles(
        _mk_cache(n_tasks=600, n_nodes=200), conf)

    assert binds_t == binds_f, (
        f"sharded compacted vs full binds diverged ({impl_env or 'shard_map'})")
    assert status_t == status_f


# --------------------------------------------------------------------------
# solve-level: forced exhaustion + direct equivalence
# --------------------------------------------------------------------------


def _session_snapshot(n_tasks, n_nodes, seed=3):
    cache = synthetic_cluster(
        n_tasks=n_tasks, n_nodes=n_nodes, gang_size=2, n_queues=2, seed=seed
    )
    conf = load_scheduler_conf(None)
    ssn = open_session(cache, conf.tiers)
    try:
        from kube_batch_tpu.actions.allocate import (
            build_session_snapshot,
            session_allocate_config,
        )

        snap, _meta = build_session_snapshot(ssn)
        config = session_allocate_config(ssn)
    finally:
        close_session(ssn)
    return snap, config


def _pend_rows(snap, bucket):
    rows = np.flatnonzero(np.asarray(snap.task_pending))
    assert 0 < rows.size <= bucket
    out = np.full(bucket, -1, np.int32)
    out[: rows.size] = rows.astype(np.int32)
    return out


def test_forced_exhaustion_fallback_bit_exact():
    """The adversarial fixture: a tiny K against hot node contention (240
    pending tasks bidding for 8 nodes) forces candidate lists to exhaust
    mid-solve.  The full-matrix re-entry must fire (counters > 0) and the
    result must still be bit-identical to the full program."""
    import jax

    from kube_batch_tpu.ops.assignment import allocate_solve, allocate_topk_solve

    snap, config = _session_snapshot(240, 8)
    full = jax.device_get(allocate_solve(snap, config))
    rows = _pend_rows(snap, 256)
    topk = jax.device_get(
        allocate_topk_solve(snap, rows, config._replace(topk=2))
    )
    for name in full._fields:
        if name.startswith("topk_"):
            continue
        assert np.array_equal(getattr(full, name), getattr(topk, name)), (
            f"exhaustion fixture diverged on {name}")
    assert int(topk.topk_exhausted) > 0, "fixture never exhausted"
    assert int(topk.topk_reentries) > 0, "full-head re-entry never fired"


def test_forced_exhaustion_sharded_bit_exact():
    """The same exhaustion fixture through the shard_map and pjit compacted
    programs on a forced 4-device mesh."""
    import jax

    from kube_batch_tpu.ops.assignment import allocate_solve
    from kube_batch_tpu.parallel.mesh import allocate_topk_solve_fn, make_mesh

    snap, config = _session_snapshot(240, 8)
    full = jax.device_get(allocate_solve(snap, config))
    rows = _pend_rows(snap, 256)
    cfg = config._replace(topk=2)
    mesh = make_mesh(4)
    with mesh:
        sm = jax.device_get(
            allocate_topk_solve_fn(mesh, cfg, impl="shard_map")(snap, rows))
        pj = jax.device_get(
            allocate_topk_solve_fn(mesh, cfg, impl="pjit")(snap, rows))
    for name in full._fields:
        if name.startswith("topk_"):
            continue
        assert np.array_equal(getattr(full, name), getattr(sm, name)), (
            f"shard_map exhaustion fixture diverged on {name}")
        assert np.array_equal(getattr(full, name), getattr(pj, name)), (
            f"pjit exhaustion fixture diverged on {name}")
    assert int(sm.topk_exhausted) > 0
    assert int(sm.topk_exhausted) == int(pj.topk_exhausted)


def test_solve_level_topk_matches_full_randomized():
    """Direct solve-level equivalence across K widths on a contended
    snapshot (no cycle machinery in the loop)."""
    import jax

    from kube_batch_tpu.ops.assignment import allocate_solve, allocate_topk_solve

    snap, config = _session_snapshot(400, 16, seed=7)
    full = jax.device_get(allocate_solve(snap, config))
    rows = _pend_rows(snap, 512)
    for k in (2, 4, 8):
        topk = jax.device_get(
            allocate_topk_solve(snap, rows, config._replace(topk=k))
        )
        for name in full._fields:
            if name.startswith("topk_"):
                continue
            assert np.array_equal(getattr(full, name), getattr(topk, name)), (
                f"K={k} diverged on {name}")


# --------------------------------------------------------------------------
# the exact-lex-top-K extraction itself
# --------------------------------------------------------------------------


def test_lex_topk_matches_reference():
    """lex_topk against a brute-force lexicographic sort, under heavy
    score AND hash ties (the adversarial regime the two-key order exists
    for), including the order of the emitted list."""
    import jax.numpy as jnp

    from kube_batch_tpu.ops.assignment import NEG, f32_sort_key, lex_topk

    rng = np.random.default_rng(5)
    P, M, K = 40, 150, 12
    score = np.round(rng.uniform(0, 3, (P, M)) * 4).astype(np.float32) / 4
    score[rng.random((P, M)) < 0.35] = NEG
    hashes = rng.integers(0, 5, (P, M)).astype(np.int32)
    skey = np.asarray(f32_sort_key(jnp.asarray(score)))
    idx0 = np.broadcast_to(np.arange(M, dtype=np.int32), (P, M)).copy()
    oi, os_, oh = lex_topk(
        jnp.asarray(skey), jnp.asarray(hashes), jnp.asarray(idx0), K, 32
    )
    oi = np.asarray(oi)
    for p in range(P):
        ref = sorted(
            range(M), key=lambda n: (-skey[p, n], -hashes[p, n], n)
        )[:K]
        assert ref == oi[p].tolist(), f"row {p} extraction order diverged"


def test_f32_sort_key_is_monotone():
    import jax.numpy as jnp

    from kube_batch_tpu.ops.assignment import f32_sort_key

    vals = np.asarray(
        [-3.0e38, -1.0e10, -1.5, -1.0, -1e-30, 0.0, 1e-30, 1.0, 2.5, 3.0e38],
        np.float32,
    )
    keys = np.asarray(f32_sort_key(jnp.asarray(vals)))
    assert (np.diff(keys) > 0).all()
    # the two zeros compare EQUAL as floats and must key equal too — a
    # custom extra_rows score emitting -0.0 must not order differently
    # from the float-comparing full-matrix oracle
    zeros = np.asarray(f32_sort_key(jnp.asarray([-0.0, 0.0], jnp.float32)))
    assert zeros[0] == zeros[1]


def test_resolve_topk_garbage_disables(_env_guard):
    from kube_batch_tpu.actions.allocate import TOPK_DEFAULT, resolve_topk

    os.environ.pop("KB_TOPK", None)
    assert resolve_topk() == TOPK_DEFAULT
    os.environ["KB_TOPK"] = "16"
    assert resolve_topk() == 16
    # a typo'd attempt to DISABLE must not silently re-enable compaction
    os.environ["KB_TOPK"] = "off"
    assert resolve_topk() == 0
    os.environ["KB_TOPK"] = "0"
    assert resolve_topk() == 0


# --------------------------------------------------------------------------
# dispatch planning: bucket ladder + ratchet
# --------------------------------------------------------------------------


def test_plan_topk_bucket_is_shape_derived(_env_guard):
    """The pending bucket is a pure function of the task-capacity shape —
    the zero-steady-retrace guarantee: no pending-count wobble can move
    the compacted program's shapes while the cache's own buckets hold."""
    from kube_batch_tpu.actions.allocate import (
        plan_topk_bucket,
        topk_bucket_for,
    )

    snap, _config = _session_snapshot(600, 48)
    capT = snap.task_req.shape[0]
    bucket = topk_bucket_for(capT)
    assert bucket is not None and bucket <= capT // 4
    # steady-state shape: a handful of pending rows in a big task bucket
    pend = np.zeros(capT, bool)
    pend[5:17] = True
    snap = snap._replace(task_pending=pend)
    rows, k = plan_topk_bucket(snap, None, 32)
    assert rows is not None and k == 32
    assert rows.shape[0] == bucket
    assert rows[11] == 16 and rows[12] == -1
    # a different pending count maps to the SAME bucket
    pend2 = np.zeros(capT, bool)
    pend2[: bucket] = True
    rows2, _ = plan_topk_bucket(snap._replace(task_pending=pend2), None, 32)
    assert rows2.shape[0] == bucket
    # pending past the bucket declines (cold start → full program)
    pend3 = np.zeros(capT, bool)
    pend3[: bucket + 1] = True
    assert plan_topk_bucket(
        snap._replace(task_pending=pend3), None, 32) == (None, 0)
    # K >= node bucket declines compaction; K=0 declines
    assert plan_topk_bucket(snap, None, 10 ** 6) == (None, 0)
    assert plan_topk_bucket(snap, None, 0) == (None, 0)
    # tiny task buckets have no compaction rung
    assert topk_bucket_for(512) is None


# --------------------------------------------------------------------------
# zero steady-state retraces + zero per-round collectives
# --------------------------------------------------------------------------


def test_zero_steady_state_retraces_compacted(_env_guard):
    """Churn cycles with the compacted dispatch on: after warmup, no jit
    entry point may retrace (the bucket ratchet makes boundary flapping
    structurally impossible)."""
    from kube_batch_tpu.utils import jitstats

    conf = load_scheduler_conf(None)
    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    cache = _mk_cache(n_tasks=600, n_nodes=200, seed=9)
    rng = np.random.default_rng(13)
    serial = itertools.count(1)

    def cycle():
        _churn(cache, rng, serial)
        ssn = open_session(cache, conf.tiers)
        try:
            for name in conf.actions:
                get_action(name).execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()

    for _ in range(3):
        cycle()
    assert get_action("allocate").last_topk is not None
    before = jitstats.total_compiles()
    for _ in range(3):
        cycle()
    assert jitstats.total_compiles() == before, (
        "steady-state retrace on the compacted path")


def test_compacted_shard_map_zero_round_collectives():
    """The compacted shard_map program's traced collective inventory:
    everything (candidate merge, ledger + node-column gathers) is
    per-solve; the round loop crosses ZERO bytes."""
    from kube_batch_tpu.analysis.jaxpr_audit import abstract_snapshot
    from kube_batch_tpu.ops.assignment import AllocateConfig
    from kube_batch_tpu.parallel.mesh import collective_stats, make_mesh

    mesh = make_mesh(8)
    st = collective_stats(
        mesh, config=AllocateConfig(topk=4),
        snap=abstract_snapshot(T=256, N=512), pend_bucket=64,
    )
    assert st["per_round_bytes"] == 0, st["ops"]["per_round"]
    assert st["ops"]["per_round"] == {}
    assert st["per_solve_bytes"] > 0
