"""Cycle tracing plane (kube_batch_tpu/obs): span semantics, Chrome
export validity, the flight recorder's anomaly windows, trace-on vs
trace-off decision bit-exactness over randomized churn, the pipelined
writeback overlap rendered as overlapping spans, the span-stamped
arrival→decision latencies, and the guard trip-rate alert evaluator."""

from __future__ import annotations

import json
import time
import urllib.request

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import metrics as prom_metrics
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.pod import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    Queue,
)
from kube_batch_tpu.api.types import PodPhase
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.cache.fake import FakeBinder, FakeEvictor, FakeStatusUpdater
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.obs.alerts import AlertEvaluator
from kube_batch_tpu.obs.recorder import FlightRecorder
from kube_batch_tpu.obs.trace import (
    Tracer,
    chrome_trace,
    tracer_of,
    validate_chrome_trace,
)
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.sim import kubelet as kl
from kube_batch_tpu.testing.synthetic import GiB


def _mk_cache(n_nodes=4, n_queues=2):
    cache = SchedulerCache(
        binder=FakeBinder(), evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
    )
    for q in range(n_queues):
        cache.add_queue(Queue(name=f"q{q}", uid=f"uq{q}", weight=q + 1))
    for i in range(n_nodes):
        cache.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": 16000.0, "memory": 64 * GiB, "pods": 110.0},
        ))
    return cache


def _mk_scheduler(cache) -> Scheduler:
    return Scheduler(cache, conf=load_scheduler_conf(None))


def _add_gang(cache, serial, size=2, n_queues=2):
    g = f"g{serial}"
    cache.add_pod_group(PodGroup(
        name=g, namespace="tr", uid=f"pg-{g}", min_member=size,
        queue=f"q{serial % n_queues}", creation_index=serial,
    ))
    for k in range(size):
        cache.add_pod(Pod(
            name=f"{g}-{k}", namespace="tr", uid=f"pod-{g}-{k}",
            requests={"cpu": 500.0, "memory": 1 * GiB},
            annotations={GROUP_NAME_ANNOTATION: g},
            phase=PodPhase.PENDING,
            creation_index=serial * 100 + k,
        ))


class _Churner:
    """Seed-deterministic churn through the real ingest surface (the
    test_pipeline idiom) — applied identically to both caches."""

    def __init__(self, cache, seed, n_queues=2):
        self.cache = cache
        self.rng = np.random.default_rng(seed)
        self.n_queues = n_queues
        self.serial = 0
        self.gangs = []

    def add_gang(self):
        self.serial += 1
        g = f"g{self.serial}"
        size = int(self.rng.integers(1, 4))
        self.cache.add_pod_group(PodGroup(
            name=g, namespace="tr", uid=f"pg-{g}", min_member=size,
            queue=f"q{int(self.rng.integers(self.n_queues))}",
            creation_index=self.serial,
        ))
        for k in range(size):
            self.cache.add_pod(Pod(
                name=f"{g}-{k}", namespace="tr", uid=f"pod-{g}-{k}",
                requests={"cpu": float(self.rng.choice([250.0, 500.0, 1000.0])),
                          "memory": 1 * GiB},
                annotations={GROUP_NAME_ANNOTATION: g},
                phase=PodPhase.PENDING,
                creation_index=self.serial * 100 + k,
            ))
        self.gangs.append(g)

    def complete_gang(self):
        if not self.gangs:
            return
        g = self.gangs.pop(int(self.rng.integers(len(self.gangs))))
        job_uid = f"tr/{g}"
        job = self.cache.jobs.get(job_uid)
        keys = sorted(job.tasks.keys()) if job is not None else []
        for key in keys:
            kl.delete_pod(self.cache, key)
        self.cache.delete_pod_group(job_uid)

    def flip_statuses(self):
        pods = [p for p in self.cache.pods.values() if p.node_name]
        if not pods:
            return
        pods.sort(key=lambda p: p.key())
        for p in pods[: int(self.rng.integers(1, 3))]:
            if p.phase == PodPhase.PENDING:
                kl.set_running(self.cache, p.key(), p.node_name)
            elif p.phase == PodPhase.RUNNING and self.rng.random() < 0.5:
                kl.set_succeeded(self.cache, p.key())

    def step(self):
        r = self.rng.random()
        if r < 0.45:
            self.add_gang()
        elif r < 0.70:
            self.complete_gang()
        else:
            self.flip_statuses()


def _observable_state(cache) -> dict:
    pg_status = {}
    for uid, job in sorted(cache.jobs.items()):
        pg = job.pod_group
        if pg is not None:
            pg_status[uid] = (pg.phase, pg.running, pg.failed, pg.succeeded)
    return {
        "binds": dict(cache.binder.binds),
        "pods": {k: (p.node_name, p.phase)
                 for k, p in sorted(cache.pods.items())},
        "pg_status": pg_status,
        "conditions": dict(cache.pod_conditions),
        "queue_statuses": dict(cache.status_updater.queue_statuses),
    }


# ---------------------------------------------------------------------------
# span mechanics
# ---------------------------------------------------------------------------


class TestSpans:
    def _tracer(self, tmp_path, **kw):
        rec = FlightRecorder(ring=16, directory=str(tmp_path),
                             post_cycles=0)
        return Tracer(recorder=rec, enabled=True, **kw), rec

    def test_nesting_builds_a_tree(self, tmp_path):
        tr, rec = self._tracer(tmp_path)
        tr.begin_cycle("test")
        with tr.span("outer"):
            with tr.span("inner_a"):
                pass
            with tr.span("inner_b") as sp:
                sp.set(k=1)
        tr.end_cycle()
        records = rec.records()
        assert len(records) == 1
        spans = records[0].spans
        assert [s.name for s in spans] == ["outer"]
        assert [c.name for c in spans[0].children] == ["inner_a", "inner_b"]
        assert spans[0].children[1].attrs == {"k": 1}
        assert spans[0].t1 >= spans[0].children[1].t1

    def test_exception_closes_the_span(self, tmp_path):
        tr, rec = self._tracer(tmp_path)
        tr.begin_cycle("test")
        with pytest.raises(RuntimeError):
            with tr.span("boom"):
                raise RuntimeError("x")
        tr.end_cycle()
        sp = rec.records()[0].spans[0]
        assert sp.t1 >= sp.t0
        assert sp.attrs["error"] == "RuntimeError"
        # the per-thread stack unwound — a follow-up span is a root again
        tr.begin_cycle("test2")
        with tr.span("after"):
            pass
        tr.end_cycle()
        assert [s.name for s in rec.records()[1].spans] == ["after"]

    def test_disabled_tracer_still_times_but_retains_nothing(self, tmp_path):
        rec = FlightRecorder(ring=4, directory=str(tmp_path))
        tr = Tracer(recorder=rec, enabled=False)
        tr.begin_cycle("test")
        with tr.span("stage") as sp:
            time.sleep(0.002)
        tr.end_cycle()
        assert sp.dur_ms > 0, "spans always stamp (metrics feed from them)"
        assert rec.records() == []
        assert tr.spans_total == 0

    def test_implicit_record_rolls_over(self, tmp_path):
        from kube_batch_tpu.obs.trace import IMPLICIT_ROLL

        tr, rec = self._tracer(tmp_path)
        for _ in range(IMPLICIT_ROLL + 5):
            with tr.span("direct"):
                pass
        assert rec.records(), "direct-driven spans must reach the ring"
        assert rec.records()[0].reason == "implicit"

    def test_virtual_time_stamps_follow_the_injected_clock(self, tmp_path):
        from kube_batch_tpu.sim.clock import VirtualClock

        clock = VirtualClock(start=7.0)
        tr, rec = self._tracer(tmp_path, clock=clock)
        tr.begin_cycle("vt")
        with tr.span("stage") as sp:
            clock.sleep(2.5)
        tr.end_cycle()
        assert sp.vt0 == 7.0 and sp.vt1 == 9.5
        assert rec.records()[0].vt0 == 7.0


# ---------------------------------------------------------------------------
# chrome export + validation
# ---------------------------------------------------------------------------


class TestChromeExport:
    def test_real_cycles_export_validates(self):
        cache = _mk_cache()
        sched = _mk_scheduler(cache)
        for s in range(1, 4):
            _add_gang(cache, s)
            sched.run_once()
        doc = chrome_trace(cache.flight_recorder.records())
        assert validate_chrome_trace(doc) == []
        names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
        assert {"session_open", "status_derive", "action:allocate",
                "solve_dispatch"} <= names
        cache.stop()

    def test_validator_rejects_unbalanced_and_negative(self):
        bad = {"traceEvents": [
            {"name": "outer", "ph": "X", "ts": 0.0, "dur": 10.0,
             "pid": 1, "tid": 1},
            {"name": "child-too-long", "ph": "X", "ts": 5.0, "dur": 50.0,
             "pid": 1, "tid": 1},
        ]}
        assert validate_chrome_trace(bad), "nesting violation must report"
        neg = {"traceEvents": [
            {"name": "n", "ph": "X", "ts": 0.0, "dur": -1.0,
             "pid": 1, "tid": 1},
        ]}
        assert validate_chrome_trace(neg)
        assert validate_chrome_trace({"traceEvents": []})


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def _record(self, tr):
        tr.begin_cycle("t")
        with tr.span("s"):
            pass
        tr.end_cycle()

    def test_dump_captures_cycles_before_and_after(self, tmp_path):
        rec = FlightRecorder(ring=8, directory=str(tmp_path), post_cycles=2)
        tr = Tracer(recorder=rec, enabled=True)
        for _ in range(5):
            self._record(tr)
        rec.trigger("test_anomaly", detail="planted")
        assert rec.dumps == [], "dump waits out the post-trigger window"
        for _ in range(2):
            self._record(tr)
        assert len(rec.dumps) == 1
        meta = json.loads(
            (tmp_path / "flight-test_anomaly-0000" / "meta.json").read_text()
        )
        assert meta["reason"] == "test_anomaly"
        assert meta["cycles_before"] == 5
        assert meta["cycles_after"] == 2
        doc = json.loads(
            (tmp_path / "flight-test_anomaly-0000" / "trace.json").read_text()
        )
        assert validate_chrome_trace(doc) == []
        # atomic publish: no temp residue next to the dump
        assert not [p for p in tmp_path.iterdir()
                    if p.name.startswith(".tmp-")]

    def test_flush_publishes_armed_captures(self, tmp_path):
        rec = FlightRecorder(ring=8, directory=str(tmp_path), post_cycles=10)
        tr = Tracer(recorder=rec, enabled=True)
        self._record(tr)
        rec.trigger("end_of_run")
        assert rec.dumps == []
        out = rec.flush()
        assert len(out) == 1 and rec.dumps == out

    def test_ring_is_bounded(self, tmp_path):
        rec = FlightRecorder(ring=4, directory=str(tmp_path))
        tr = Tracer(recorder=rec, enabled=True)
        for _ in range(10):
            self._record(tr)
        stats = rec.stats()
        assert stats["cycles_resident"] == 4
        assert stats["cycles_recorded"] == 10

    def test_budget_shed_triggers_a_dump(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KB_CYCLE_BUDGET", "0.000001")
        monkeypatch.setenv("KB_TRACE_DIR", str(tmp_path))
        monkeypatch.setenv("KB_TRACE_POST", "1")
        cache = _mk_cache()
        sched = _mk_scheduler(cache)
        _add_gang(cache, 1)
        sched.run_once_pipelined()  # overruns the 1µs budget → shed
        sched.run_once_pipelined()  # the post-trigger cycle
        sched.drain_pipeline()
        assert cache.flight_recorder.dumps, "shed must arm a flight dump"
        reasons = [t["reason"] for t in cache.flight_recorder.triggers]
        assert "budget_shed" in reasons
        cache.stop()


# ---------------------------------------------------------------------------
# inertness: trace on vs off — bit-identical decisions
# ---------------------------------------------------------------------------


class TestTraceInert:
    @pytest.mark.parametrize("seed", [3, 17])
    def test_trace_on_vs_off_decisions_identical(self, seed, monkeypatch):
        """Tracing must be provably inert: the same churn stream under
        KB_TRACE=1 and KB_TRACE=0 produces identical binds, statuses,
        conditions, and queue writebacks (serial and pipelined bodies)."""
        monkeypatch.setenv("KB_TRACE", "0")
        c_off = _mk_cache()
        s_off = _mk_scheduler(c_off)
        assert not c_off.tracer.enabled
        monkeypatch.setenv("KB_TRACE", "1")
        c_on = _mk_cache()
        s_on = _mk_scheduler(c_on)
        assert c_on.tracer.enabled
        ch_off, ch_on = _Churner(c_off, seed), _Churner(c_on, seed)
        for _ in range(3):
            ch_off.add_gang()
            ch_on.add_gang()
        for cycle in range(8):
            ch_off.step()
            ch_on.step()
            if cycle % 2:
                s_off.run_once()
                s_on.run_once()
            else:
                s_off.run_once_pipelined()
                s_off.drain_pipeline()
                s_on.run_once_pipelined()
                s_on.drain_pipeline()
        assert _observable_state(c_on) == _observable_state(c_off)
        # and the traced side actually traced
        assert c_on.tracer.cycles_total >= 8
        assert c_on.tracer.spans_total > 0
        c_off.stop()
        c_on.stop()


# ---------------------------------------------------------------------------
# the pipelined overlap, visible in the trace
# ---------------------------------------------------------------------------


class TestPipelinedOverlap:
    def test_writeback_span_overlaps_next_cycle_compute(self):
        """Cycle N's writeback span (its own worker-thread track) must
        overlap cycle N+1's session_open span in wall time — the exported
        trace renders the pipeline's overlap structure directly."""
        cache = _mk_cache()
        sched = _mk_scheduler(cache)
        _add_gang(cache, 1)
        sched.run_once_pipelined()  # warm compile out of the way
        orig_flush = cache.flush_binds

        def slow_flush():
            time.sleep(0.08)
            return orig_flush()

        cache.flush_binds = slow_flush
        _add_gang(cache, 2)
        sched.run_once_pipelined()   # cycle N: hands writeback to worker
        _add_gang(cache, 3)
        sched.run_once_pipelined()   # cycle N+1 computes under N's egress
        sched.drain_pipeline()
        records = cache.flight_recorder.records()
        wb = None
        nxt_open = None
        for i, rec in enumerate(records):
            wb_spans = [s for s in rec.spans if s.name == "writeback"]
            if wb_spans and i + 1 < len(records):
                opens = [s for s in records[i + 1].spans
                         if s.name == "session_open"]
                if opens:
                    wb, nxt_open = wb_spans[-1], opens[0]
                    if wb.t0 < nxt_open.t1 and nxt_open.t0 < wb.t1:
                        break
        assert wb is not None and nxt_open is not None
        assert wb.t0 < nxt_open.t1 and nxt_open.t0 < wb.t1, (
            "writeback must overlap the next cycle's compute"
        )
        assert wb.tid != nxt_open.tid, "writeback rides its own thread track"
        # and the chrome export of exactly this structure validates
        assert validate_chrome_trace(chrome_trace(records)) == []
        cache.stop()


# ---------------------------------------------------------------------------
# span-stamped arrival→decision latencies (satellite: latency-sink tests)
# ---------------------------------------------------------------------------


def _span_stamped_latencies(cache):
    out = []
    for rec in cache.flight_recorder.records():
        out.extend(rec.attrs.get("decision_lat_ms", ()))
    tr = cache.tracer
    with tr._mu:
        cur = tr.current
    if cur is not None:
        out.extend(cur.attrs.get("decision_lat_ms", ()))
    return out


class TestDecisionLatencySink:
    def test_direct_path_sink_and_spans_agree(self):
        """Direct (unstaged) ingest: every histogram/sink sample has a
        span-stamped twin on the cycle's trace record."""
        sink = []
        prom_metrics.set_decision_latency_sink(sink)
        try:
            cache = _mk_cache()
            sched = _mk_scheduler(cache)
            _add_gang(cache, 1)
            _add_gang(cache, 2)
            sched.run_once()
        finally:
            prom_metrics.set_decision_latency_sink(None)
        assert len(sink) == 4, "both 2-gangs decided"
        stamped = _span_stamped_latencies(cache)
        assert sorted(round(v, 3) for v in sink) == sorted(stamped)
        cache.stop()

    def test_staged_path_sink_and_spans_agree(self):
        """Staged ingest (the pipelined mode's path): the sink drains the
        same samples, and the stage-time arrival clock means the latency
        covers the stage→drain wait; span stamps match exactly."""
        sink = []
        prom_metrics.set_decision_latency_sink(sink)
        try:
            cache = _mk_cache()
            sched = _mk_scheduler(cache)
            cache.enable_ingest_staging()
            _add_gang(cache, 1)           # staged, not applied
            assert "tr/g1-0" in cache._arrival_ts
            time.sleep(0.01)              # a real stage→drain wait
            sched.run_once_pipelined()
            sched.drain_pipeline()
        finally:
            prom_metrics.set_decision_latency_sink(None)
            cache.disable_ingest_staging()
        assert len(sink) == 2
        assert min(sink) * 1.0 >= 10.0, (
            "stage-time clock must cover the stage→drain wait"
        )
        stamped = _span_stamped_latencies(cache)
        assert sorted(round(v, 3) for v in sink) == sorted(stamped)
        cache.stop()

    def test_slo_breach_arms_a_flight_dump(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KB_TRACE_SLO_MS", "0.000001")
        monkeypatch.setenv("KB_TRACE_DIR", str(tmp_path))
        # the breach fires MID-cycle (at the bind decision), so the
        # triggering cycle itself is the first post-trigger capture
        monkeypatch.setenv("KB_TRACE_POST", "1")
        cache = _mk_cache()
        sched = _mk_scheduler(cache)
        _add_gang(cache, 1)
        sched.run_once()
        reasons = [t["reason"] for t in cache.flight_recorder.triggers]
        assert "slo_breach" in reasons
        assert cache.flight_recorder.dumps
        cache.stop()


# ---------------------------------------------------------------------------
# guard trip-rate alerting (obs/alerts)
# ---------------------------------------------------------------------------


class TestAlerts:
    def _plane(self):
        from kube_batch_tpu.guard.plane import GuardPlane

        return GuardPlane(enabled=True, audit_every=0, cooldown=4)

    def test_threshold_fires_and_resolves(self):
        gp = self._plane()
        ev = AlertEvaluator(threshold=2, window=4)
        gp.trip("allocate", ["topk"], reason="invariant", detail="t1")
        gp.end_cycle()
        fire = ev.evaluate(gp)
        assert fire.get("guard_trips") is False, "one trip under threshold"
        gp.trip("allocate", ["topk"], reason="invariant", detail="t2")
        gp.end_cycle()
        fire = ev.evaluate(gp)
        assert fire["guard_trips"] is True
        assert fire["guard_trips:topk"] is True
        assert ev.state()["alerts"]["guard_trips"]["fired_total"] == 1
        # the window slides past both trips → the alert resolves
        for _ in range(6):
            gp.end_cycle()
        fire = ev.evaluate(gp)
        assert fire["guard_trips"] is False
        assert ev.state()["alerts"]["guard_trips"]["fired_total"] == 1

    def test_gauge_follows_firing_state(self):
        from kube_batch_tpu.metrics.metrics import ALERTS_FIRING

        gp = self._plane()
        ev = AlertEvaluator(threshold=1, window=8)
        gp.trip("reclaim", ["shard_map"], reason="audit", detail="x")
        gp.end_cycle()
        ev.evaluate(gp)
        assert ALERTS_FIRING._values[("guard_trips",)] == 1.0
        assert ALERTS_FIRING._values[("guard_trips:shard_map",)] == 1.0

    def test_scheduler_cycle_evaluates_alerts(self, monkeypatch):
        """The L1 loop evaluates alerts on the guard's cycle clock — a
        corruption-style trip surfaces at /v1/alerts with no extra
        wiring."""
        monkeypatch.setenv("KB_ALERT_GUARD_TRIPS", "1")
        cache = _mk_cache()
        sched = _mk_scheduler(cache)
        _add_gang(cache, 1)
        sched.run_once()  # attaches the guard plane via the dispatch
        gp = cache.guard_plane
        gp.trip("allocate", ["topk"], reason="invariant", detail="planted")
        sched.run_once()
        st = cache.alert_evaluator.state()
        assert st["alerts"]["guard_trips"]["firing"] is True
        cache.stop()


# ---------------------------------------------------------------------------
# HTTP surfaces
# ---------------------------------------------------------------------------


class TestTraceEndpoints:
    def test_v1_trace_and_alerts(self):
        from kube_batch_tpu.cmd.server import AdminServer

        cache = _mk_cache()
        sched = _mk_scheduler(cache)
        _add_gang(cache, 1)
        sched.run_once()
        admin = AdminServer(cache, "127.0.0.1", 0)
        admin.start()
        try:
            base = f"http://127.0.0.1:{admin.port}"
            with urllib.request.urlopen(base + "/v1/trace") as r:
                trace = json.loads(r.read())
            assert trace["enabled"] is True
            assert trace["cycles_traced"] >= 1
            assert trace["last_cycle"] is not None
            names = {s["name"] for s in trace["last_cycle"]["spans"]}
            assert "session_open" in names
            assert trace["ring"]["capacity"] >= 2
            with urllib.request.urlopen(base + "/v1/alerts") as r:
                alerts = json.loads(r.read())
            assert "alerts" in alerts and "window_cycles" in alerts
            # the per-stage histogram rides /metrics
            with urllib.request.urlopen(base + "/metrics") as r:
                text = r.read().decode()
            assert "volcano_cycle_stage_latency_milliseconds" in text
        finally:
            admin.stop()
            cache.stop()
