"""Tier C HBM audit: the production registry must be clean-or-allowlisted
at every shape-ladder point, and each rule must catch its planted bug — an
over-budget program, a steady-path full-matrix temporary, a declared-but-
unrealized donation, and a per-round collective whose payload scales with
the node axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import ShapeDtypeStruct as S
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from kube_batch_tpu.analysis.jaxpr_audit import (
    REGISTRY,
    EntryPoint,
    ShapePoint,
    sharded_registry,
)
from kube_batch_tpu.analysis.hbm_audit import (
    GIB,
    HBM_ALLOWLIST,
    HBM_RULES,
    _glob_match,
    audit_entry_at,
    budget_bytes,
    headroom_report,
    peak_live_bytes,
    run_hbm_audit,
    shape_points,
)

# a fixture shape point with UNAMBIGUOUS axis extents: task dims resolve to
# {4096, 2048, 1024}, node dims to {512, 256} (T//8 = 512 collides with N
# and is correctly claimed by the node axis) — see hbm_audit._axis_dims
_SP = ShapePoint(
    name="fixture", tasks=4000, nodes=500, T=4096, N=512, J=8, Q=2, R=3,
    W=1, K_aff=1, P=1024, topk=2, warm_w=4, warm_c=4, warm_pi=4,
    probe_b=2, probe_g=4, scatter_rows=8,
)


def _entry(name, build, **kw):
    return EntryPoint(name=name, build=build, **kw)


def _rules(report):
    return [r for r, _ in report.findings]


def _tn_outer_build(sp=None):
    # materializes a [T, N] outer product — the planted full-matrix plane
    fn = jax.jit(lambda a, b: (a[:, None] * b[None, :]).sum())
    return fn, (S((4096,), jnp.float32), S((512,), jnp.float32))


def _mesh4():
    return Mesh(np.array(jax.devices()[:4]), ("nodes",))


class TestShapeLadder:
    def test_three_points_including_the_north_star(self):
        pts = {sp.name: sp for sp in shape_points()}
        assert len(pts) >= 3
        ns = pts["northstar-1m"]
        assert ns.tasks == 1_000_000 and ns.nodes == 100_000
        assert ns.T >= 1_000_000 and ns.N >= 100_000
        # the compacted candidate geometry: P stays well under T
        assert ns.P <= ns.T // 4
        assert "headline-50k" in pts


class TestSelfEnforcement:
    def test_single_device_registry_clean_at_all_points(self):
        findings = run_hbm_audit(registry=tuple(REGISTRY))
        assert findings == [], "\n" + "\n".join(f.render() for f in findings)

    def test_sharded_registry_clean_at_the_north_star(self):
        sharded = sharded_registry()
        assert sharded, "conftest's forced 8-device mesh missing"
        pts = [sp for sp in shape_points() if sp.name == "northstar-1m"]
        findings = run_hbm_audit(registry=sharded, points=pts)
        assert findings == [], "\n" + "\n".join(f.render() for f in findings)

    @pytest.mark.slow
    def test_full_ladder_clean(self):
        findings = run_hbm_audit()
        assert findings == [], "\n" + "\n".join(f.render() for f in findings)

    def test_steady_entries_hold_the_sparse_contract_at_scale(self):
        """The acceptance criterion in words: the steady-path allocate /
        gate / scatter programs carry ZERO unsuppressed [T, N] temporaries
        at 1M×100k — the only KBT202 waivers are the named ROADMAP 1
        corners (evict bids, topk/warm build+fallback planes)."""
        for e_pat, rule, _pt in HBM_ALLOWLIST:
            if rule != "KBT202":
                continue
            assert (
                "evict" in e_pat or "topk" in e_pat or "warm" in e_pat
            ), f"unexpected steady-path KBT202 waiver: {e_pat}"
        for key, reason in HBM_ALLOWLIST.items():
            assert "ROADMAP" in reason, f"waiver without a burn-down " \
                f"cross-reference: {key}"


class TestPlantedBugs:
    def test_planted_over_budget_program_is_detected(self):
        rep = audit_entry_at(
            _entry("planted.big", _tn_outer_build), _SP,
            budget=1024, label="1 KiB (test)")
        assert _rules(rep) == ["KBT201"]
        assert "exceed" in rep.findings[0][1]
        assert "fixture" in rep.findings[0][1]

    def test_planted_tn_temporary_in_a_steady_program_is_detected(self):
        rep = audit_entry_at(
            _entry("planted.tn", _tn_outer_build, steady=True), _SP)
        assert _rules(rep) == ["KBT202"]
        msg = rep.findings[0][1]
        assert "T=4096" in msg and "N=512" in msg

    def test_the_same_plane_passes_when_not_steady(self):
        # full-matrix oracles are allowed their planes — KBT202 is a
        # steady-path contract, not a blanket ban
        rep = audit_entry_at(_entry("planted.cold", _tn_outer_build), _SP)
        assert rep.traced and _rules(rep) == []

    def test_compacted_geometry_steady_program_passes(self):
        def build(sp=None):
            # [P, topk] candidate table — the shape the contract wants
            fn = jax.jit(lambda t: (t * 2.0).sum(axis=1))
            return fn, (S((1024, 2), jnp.float32),)

        rep = audit_entry_at(
            _entry("planted.sparse", build, steady=True), _SP)
        assert rep.traced and _rules(rep) == []

    def test_planted_unrealized_donation_is_detected(self):
        def build(sp=None):
            fn = jax.jit(lambda d: d.sum(), donate_argnums=(0,))
            return fn, (S((4096, 512), jnp.float32),)

        rep = audit_entry_at(
            _entry("planted.donation", build, donate={"*": (0,)}), _SP)
        assert _rules(rep) == ["KBT203"]
        assert "no shape/dtype-matching output" in rep.findings[0][1]

    def test_realized_donation_passes(self):
        def build(sp=None):
            fn = jax.jit(lambda d, r: d.at[r].set(0.0), donate_argnums=(0,))
            return fn, (S((4096, 512), jnp.float32), S((2,), jnp.int32))

        rep = audit_entry_at(
            _entry("planted.donation_ok", build, donate={"*": (0,)}), _SP)
        assert rep.traced and _rules(rep) == []

    def test_planted_node_scaled_round_collective_is_detected(self):
        def build(sp=None):
            mesh = _mesh4()

            def body(x):  # x: local [N/4]
                def step(c, _):
                    g = jax.lax.all_gather(c, "nodes", tiled=True)  # [N]
                    return c + g.sum(), None

                c, _ = jax.lax.scan(step, x, None, length=3)
                return c

            fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P("nodes"),
                                   out_specs=P("nodes")))
            return fn, (S((512,), jnp.float32),)

        rep = audit_entry_at(_entry("planted.gather", build), _SP)
        assert _rules(rep) == ["KBT204"]
        msg = rep.findings[0][1]
        assert "all_gather" in msg and "N=512" in msg

    def test_per_solve_collective_passes(self):
        # the same gather OUTSIDE the round loop is the allowed one-time
        # node-ledger pattern
        def build(sp=None):
            mesh = _mesh4()

            def body(x):
                g = jax.lax.all_gather(x, "nodes", tiled=True)
                return x + g.sum()

            fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P("nodes"),
                                   out_specs=P("nodes")))
            return fn, (S((512,), jnp.float32),)

        rep = audit_entry_at(_entry("planted.solve_gather", build), _SP)
        assert rep.traced and _rules(rep) == []

    def test_broken_entry_names_the_shape_point_instead_of_crashing(self):
        def build(sp=None):
            raise ValueError("shape-derived python branch blew up")

        rep = audit_entry_at(_entry("planted.broken", build), _SP)
        assert not rep.traced
        assert _rules(rep) == ["KBT000"]
        msg = rep.findings[0][1]
        assert "failed to trace" in msg and "fixture" in msg
        # and the tier driver surfaces it as a finding, not an exception
        findings = run_hbm_audit(
            registry=[_entry("planted.broken", build)], points=[_SP],
            allowlist={})
        assert [f.rule for f in findings] == ["KBT000"]


class TestAllowlist:
    def _tn_entry(self):
        return _entry("planted.tn", _tn_outer_build, steady=True)

    def test_allow_with_reason_suppresses(self):
        allow = {("planted.tn", "KBT202", "fixture"): "fixture: deliberate"}
        findings = run_hbm_audit(
            registry=[self._tn_entry()], points=[_SP], allowlist=allow)
        assert findings == []

    def test_allow_without_reason_is_itself_a_finding(self):
        allow = {("planted.tn", "KBT202", "fixture"): "   "}
        findings = run_hbm_audit(
            registry=[self._tn_entry()], points=[_SP], allowlist=allow)
        assert [f.rule for f in findings] == ["KBT000"]
        assert "no reason" in findings[0].message

    def test_stale_allowlist_entry_is_itself_a_finding(self):
        def build(sp=None):
            fn = jax.jit(lambda x: x + 1.0)
            return fn, (S((256,), jnp.float32),)

        allow = {("planted.clean", "KBT202", "fixture"): "was fixed"}
        findings = run_hbm_audit(
            registry=[_entry("planted.clean", build, steady=True)],
            points=[_SP], allowlist=allow)
        assert [f.rule for f in findings] == ["KBT000"]
        assert "stale" in findings[0].message

    def test_uncovered_allowlist_entry_is_not_judged_stale(self):
        # a single-device run must not flag sharded-namespace waivers
        def build(sp=None):
            fn = jax.jit(lambda x: x + 1.0)
            return fn, (S((256,), jnp.float32),)

        allow = {("parallel.mesh.not_in_this_run", "KBT202", "*"): "r"}
        findings = run_hbm_audit(
            registry=[_entry("planted.clean", build)], points=[_SP],
            allowlist=allow)
        assert findings == []

    def test_wildcard_points_cover_the_whole_ladder(self):
        allow = {("planted.tn", "KBT202", "*"): "fixture: deliberate"}
        findings = run_hbm_audit(
            registry=[self._tn_entry()], points=[_SP], allowlist=allow)
        assert findings == []

    def test_select_filters_hbm_rules_but_keeps_meta(self):
        findings = run_hbm_audit(
            registry=[self._tn_entry()], points=[_SP], allowlist={},
            select=["KBT201"])
        assert findings == []
        findings = run_hbm_audit(
            registry=[self._tn_entry()], points=[_SP], allowlist={},
            select=["KBT202"])
        assert [f.rule for f in findings] == ["KBT202"]

    def test_glob_matches_literal_brackets(self):
        # entry names contain literal [impl] tags — fnmatch would read
        # them as character classes and silently never match
        assert _glob_match("ops.eviction.evict_solve[reclaim]",
                           "ops.eviction.evict_solve[*]")
        assert _glob_match("ops.eviction.evict_solve[preempt]",
                           "ops.eviction.evict_solve[*]")
        assert not _glob_match("ops.eviction.evict_solver",
                               "ops.eviction.evict_solve[*]")
        assert _glob_match("anything at all", "*")
        assert not _glob_match("kbt202", "KBT202")


class TestBudget:
    def test_default_budget_is_a_v5e(self, monkeypatch):
        monkeypatch.delenv("KB_HBM_BUDGET", raising=False)
        assert budget_bytes() == (16 * GIB, "v5e")

    def test_profile_override(self, monkeypatch):
        monkeypatch.setenv("KB_HBM_BUDGET", "v6e")
        assert budget_bytes() == (32 * GIB, "v6e")

    def test_gib_override(self, monkeypatch):
        monkeypatch.setenv("KB_HBM_BUDGET", "24")
        b, label = budget_bytes()
        assert b == 24 * GIB and "24" in label

    def test_garbage_override_falls_back_hard(self, monkeypatch):
        # the audit must never silently relax to an infinite budget
        monkeypatch.setenv("KB_HBM_BUDGET", "plenty")
        assert budget_bytes() == (16 * GIB, "v5e")


class TestLiveness:
    def test_donation_credit_lowers_the_peak(self):
        closed = jax.jit(lambda d: d * 2.0 + 1.0).trace(
            S((1024, 1024), jnp.float32)).jaxpr
        undonated = peak_live_bytes(closed)
        donated = peak_live_bytes(closed, donated_flat={0})
        # 4 MiB input frees after its last read instead of surviving
        assert donated == undonated - 4 * 2**20

    def test_cond_charges_the_max_branch_not_the_sum(self):
        def big(v):
            return (v * 2.0).sum()

        closed = jax.jit(
            lambda p, x: jax.lax.cond(p, big, big, x)).trace(
            S((), jnp.bool_), S((1024, 1024), jnp.float32)).jaxpr
        peak = peak_live_bytes(closed)
        # 4 MiB operand + ONE 4 MiB branch temporary (+ scalars)
        assert 8 * 2**20 <= peak < 9 * 2**20

    def test_shard_map_charges_per_device_bytes(self):
        mesh = _mesh4()
        fn = jax.jit(shard_map(lambda x: x * 2.0, mesh=mesh,
                               in_specs=P("nodes"), out_specs=P("nodes")))
        closed = fn.trace(S((512,), jnp.float32)).jaxpr
        peak = peak_live_bytes(closed)
        # one device holds [128] in + [128] body temp + [128] out, far
        # under the 2 × 2 KiB an unsharded walk would charge
        assert 0 < peak <= 2048

    def test_headroom_report_structure(self):
        def build(sp=None):
            return jax.jit(lambda x: x + 1.0), (S((256,), jnp.float32),)

        rep = headroom_report(
            registry=[_entry("planted.report", build)], points=[_SP])
        assert rep["budget_bytes"] > 0
        d = rep["entries"]["planted.report"]["fixture"]
        assert d["traced"] and d["peak_bytes"] > 0
        assert d["headroom_bytes"] == rep["budget_bytes"] - d["peak_bytes"]
        assert d["over_budget"] is False and d["findings"] == []


class TestNestedCollectiveInventory:
    """The jitstats extension behind KBT204's byte formulas: collectives in
    loops nested WITHIN the round loop amplify by their trip counts."""

    def _trace(self, inner):
        mesh = _mesh4()

        def body(x):
            def round_step(c, _):
                if inner == "scan":
                    def merge(m, _):
                        return m + jax.lax.psum(m, "nodes"), None

                    m, _ = jax.lax.scan(merge, c, None, length=5)
                else:
                    m = jax.lax.while_loop(
                        lambda s: s.sum() < 10.0,
                        lambda s: s + jax.lax.psum(s, "nodes"), c)
                return m, None

            c, _ = jax.lax.scan(round_step, x, None, length=2)
            return c

        # check_rep=False: shard_map has no replication rule for `while`
        fn = jax.jit(shard_map(body, mesh=mesh, in_specs=P("nodes"),
                               out_specs=P("nodes"), check_rep=False))
        return fn.trace(S((512,), jnp.float32)).jaxpr

    def test_inner_scan_trip_count_amplifies_per_round_bytes(self):
        from kube_batch_tpu.utils.jitstats import collective_inventory

        inv = collective_inventory(self._trace("scan"), detail=True)
        # one psum of a local [128] f32 = 512 B per site
        assert inv["ops"]["per_round"]["psum"]["bytes"] == 512
        assert inv["per_round_bytes"] == 512
        assert inv["per_round_bytes_expanded"] == 512 * 5
        assert inv["per_round_has_unbounded_inner_loop"] is False
        (site,) = inv["sites"]
        assert site["depth"] == 2 and site["inner_trips"] == 5
        assert site["unbounded_trips"] is False

    def test_inner_while_marks_the_formula_as_a_floor(self):
        from kube_batch_tpu.utils.jitstats import collective_inventory

        inv = collective_inventory(self._trace("while"), detail=True)
        assert inv["per_round_bytes"] == 512
        # no static trip count: ×1 in the expanded total, flagged
        assert inv["per_round_bytes_expanded"] == 512
        assert inv["per_round_has_unbounded_inner_loop"] is True
        (site,) = inv["sites"]
        assert site["unbounded_trips"] is True


class TestCatalog:
    def test_hbm_rules_documented(self):
        assert set(HBM_RULES) == {"KBT201", "KBT202", "KBT203", "KBT204"}
        for title in HBM_RULES.values():
            assert title
