"""Virtual-time simulator suite (kube_batch_tpu/sim) — the tier-1 smoke
config: small cluster, tens of virtual cycles, real Scheduler/cache
underneath. Pins the determinism contract (same seed ⇒ byte-identical
trace), fault convergence (node crash mid-gang → gang re-placed, no
accounting drift), the injected-binder-failure resync path, and trace
replayability."""

import json

from kube_batch_tpu.sim import SimConfig, SimRunner, preset, run_preset
from kube_batch_tpu.sim.workload import trace_arrivals


class TestSimSmoke:
    def test_smoke_deterministic_and_complete(self, tmp_path):
        """`--seed 7 --preset smoke` twice: byte-identical traces, full
        workload drain, longitudinal percentiles, clean invariants."""
        a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
        r1 = run_preset("smoke", seed=7, trace_path=a)
        r2 = run_preset("smoke", seed=7, trace_path=b)
        assert r1["trace_sha256"] == r2["trace_sha256"]
        assert (tmp_path / "a.jsonl").read_bytes() == (
            tmp_path / "b.jsonl").read_bytes()
        # a different seed is a different run
        assert run_preset("smoke", seed=8, cycles=12)[
            "trace_sha256"] != r1["trace_sha256"]
        # full drain + the longitudinal report
        assert r1["jobs"]["submitted"] > 0
        assert r1["jobs"]["completed"] == r1["jobs"]["submitted"]
        assert r1["jct_vt"]["p50"] > 0 and r1["jct_vt"]["p99"] >= r1["jct_vt"]["p50"]
        assert r1["wait_vt"]["n"] == r1["jobs"]["submitted"]
        assert r1["makespan_vt"] and r1["binds"] > 0
        assert r1["invariants"]["errors"] == []
        # per-queue fairness series: every cycle carries share + entitlement
        series = r1["fairness_series"]
        assert len(series) == r1["cycles_run"]
        for q, rec in series[-1]["queues"].items():
            assert 0.0 <= rec["share"] <= 1.0
            assert 0.0 < rec["entitlement"] < 1.0
        # ... and the same samples are surfaced live through /metrics as
        # volcano_queue_* gauges (the last cycle's window)
        from kube_batch_tpu.metrics import metrics as M

        rendered = M.render_prometheus()
        for q, rec in series[-1]["queues"].items():
            assert f'volcano_queue_dominant_share{{queue="{q}"}}' in rendered
            assert f'volcano_queue_share_entitlement{{queue="{q}"}}' in rendered
            # the gauge carries the most recent run's window — a valid
            # share in [0, 1] and the exact (run-invariant) entitlement
            assert 0.0 <= M.QUEUE_SHARE._values[(q,)] <= 1.0
            assert M.QUEUE_ENTITLEMENT._values[(q,)] == rec["entitlement"]

    def test_trace_replay_reproduces_run(self, tmp_path):
        """A recorded trace's JOB_ARRIVAL events re-drive an identical run
        (trace-driven workload — the recordable/replayable contract)."""
        path = str(tmp_path / "rec.jsonl")
        original = run_preset("smoke", seed=7, trace_path=path)
        cfg = preset("smoke", seed=7)
        cfg.arrivals = trace_arrivals(path)
        replay = SimRunner(cfg).run()
        assert replay["trace_sha256"] == original["trace_sha256"]

    def test_cli_emits_json_report(self, capsys):
        from kube_batch_tpu.sim.__main__ import main

        rc = main(["--preset", "smoke", "--seed", "7", "--cycles", "25",
                   "--no-fairness-series"])
        assert rc == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["metric"] == "sim_smoke_makespan_vt"
        assert rep["unit"] == "virtual_seconds"
        assert "fairness_series" not in rep


class TestSimFaults:
    def test_node_crash_mid_gang_recovers(self):
        """The fault preset: the busiest node crashes under running gangs;
        the displaced gangs must be re-placed (or completed) by the end,
        the node returns, and cache accounting shows no drift."""
        r = run_preset("fault", seed=3)
        rec = r["fault_recovery"]
        assert rec["displaced_jobs"], "crash displaced nothing — vacuous run"
        assert rec["recovered"], rec
        assert all(v in ("re-placed", "completed")
                   for v in rec["displaced_jobs"].values()), rec
        assert rec["nodes_still_down"] == []
        assert r["invariants"]["errors"] == []

    def test_reincarnated_pod_ignores_stale_lifecycle_events(self):
        """A crash-lost replica's queued POD_SUCCEEDED must not complete
        its reincarnation early: lifecycle events are uid-pinned to one
        incarnation, so the rerun serves its FULL duration after
        re-placement."""
        from kube_batch_tpu.sim.faults import node_crash_script
        from kube_batch_tpu.sim.workload import fixed_gangs

        cfg = SimConfig(
            seed=0, n_nodes=2, node_cpu=8000.0, queues=(("q0", 1),),
            cycles=40, n_jobs=0,
            arrivals=fixed_gangs(t=0.5, n_gangs=1, gang_size=2, cpu=4000.0,
                                 mem=2**30, duration=10.0, queues=("q0",)),
            faults=tuple(node_crash_script(t=2.0, down_for=2.0,
                                           pod_fail_after=1.0)),
        )
        r = SimRunner(cfg).run()
        assert r["fault_recovery"]["recovered"], r["fault_recovery"]
        assert r["jobs"]["completed"] == 1
        # displaced replica restarts ~t≥5 and runs its full 10 vt: the job
        # completes after ~15, not at the first incarnation's ~11.5 mark
        assert r["jct_vt"]["p50"] > 12.0, r["jct_vt"]
        assert r["invariants"]["errors"] == []

    def test_injected_bind_failures_converge_via_resync(self):
        """The churn preset injects binder failures + a watch flap: failed
        binds take the cache's resync repair path and the workload still
        fully completes with clean invariants."""
        r = run_preset("churn", seed=1)
        assert r["bind_failures_injected"] > 0
        assert r["jobs"]["completed"] == r["jobs"]["submitted"]
        assert r["invariants"]["errors"] == []

    def test_preemption_frees_capacity_for_high_priority(self):
        """Preemption in virtual time (default evict_recreates=False, the
        reference e2e's bare-pod semantics): a high-priority singleton
        arriving into a full cluster evicts low-priority gang slack after
        the eviction-termination delay and completes on the freed
        capacity; churn is counted."""
        from kube_batch_tpu.sim.workload import fixed_gangs

        arrivals = fixed_gangs(t=0.5, n_gangs=1, gang_size=4, cpu=4000.0,
                               mem=2**30, duration=300.0, queues=("q0",),
                               name_prefix="low")
        # gang slack 2 (minMember 2, 4 replicas): victims the gang plugin
        # permits, like e2e's scenario_preemption
        arrivals[0].data["min_member"] = 2
        # high-priority singleton needs capacity only an eviction can free
        high = fixed_gangs(t=5.0, n_gangs=1, gang_size=1, cpu=4000.0,
                           mem=2**30, duration=5.0, queues=("q0",),
                           name_prefix="high")
        for e in high:
            for t in e.data["tasks"]:
                t["priority"] = 1000
        # default conf = the shipped 5-action pipeline (includes preempt)
        cfg = SimConfig(
            seed=0, n_nodes=1, node_cpu=16000.0, queues=(("q0", 1),),
            cycles=30, n_jobs=0, arrivals=arrivals + high,
        )
        r = SimRunner(cfg).run()
        assert r["evictions"] >= 1
        assert r["invariants"]["errors"] == []
        # the high-priority job ran to completion on the freed capacity
        assert r["jobs"]["completed"] >= 1

    def test_apiserver_brownout_degrades_without_stalling(self):
        """The brownout preset: every egress call fails for a virtual-time
        window. The breaker must open (fail-fast), the degraded cycle must
        park decisions in the resync queue and KEEP TICKING through the
        window, and the workload must fully drain after it — with zero
        duplicate binds."""
        r = run_preset("brownout", seed=2)
        # the loop ticked through and past the brownout window (t=6..14)
        assert r["cycles_run"] >= 16, r["cycles_run"]
        assert r["jobs"]["completed"] == r["jobs"]["submitted"]
        # breaker story: opened during the window, closed after it
        trans = r["transport"]["breaker_transitions"]
        assert trans.get("open", 0) >= 1 and trans.get("half-open", 0) >= 1
        assert r["transport"]["breaker_state"] == "closed"
        # decisions were parked (breaker fail-fast), not hammered
        assert r["resync"]["parked_by_reason"].get("breaker-open", 0) > 0
        assert r["resync"]["depth"] == 0          # all repaired by the end
        assert r["resync"]["quarantined"] == 0    # nothing poisoned
        assert r["bind_integrity"]["duplicate_binds"] == 0
        assert r["invariants"]["errors"] == []

    def test_bind_storm_no_lost_or_duplicate_binds(self):
        """The bind-storm preset: 120 gangs (~280 pods) land in a burst
        while the binder flaps (injected failures + a short brownout).
        Recovery invariants: every gang completes (no lost binds), no pod
        is bound twice (no duplicate binds), and pod-arrival→bind p99 stays
        bounded despite the flapping."""
        r = run_preset("bind-storm", seed=0)
        assert r["jobs"]["submitted"] == 120
        assert r["jobs"]["completed"] == r["jobs"]["submitted"]
        bi = r["bind_integrity"]
        assert bi["duplicate_binds"] == 0
        assert bi["acked_binds"] == bi["unique_pods_bound"]
        lat = r["pod_bind_latency_vt"]
        assert lat["n"] >= 280 and lat["p99"] < 20.0, lat
        assert r["transport"]["breaker_transitions"].get("open", 0) >= 1
        assert r["invariants"]["errors"] == []

    def test_leader_failover_warm_standby_keeps_resident_cache(self):
        """The leader-failover preset: leadership is lost mid-run; the warm
        standby takes over through cache.failover_recover. Revalidation
        must KEEP the resident device cache (mode=warm, version token
        intact), the cluster must recover within bounded cycles, and the
        workload must drain with clean invariants."""
        r = run_preset("leader-failover", seed=5)
        assert r["jobs"]["completed"] == r["jobs"]["submitted"]
        fo = r["failover"]
        assert len(fo) == 1
        assert fo[0]["mode"] == "warm", fo
        assert fo[0]["resident_tokens"].get("single", 0) > 0
        assert fo[0]["recovery_cycles"] is not None
        assert fo[0]["recovery_cycles"] <= 20
        assert r["bind_integrity"]["duplicate_binds"] == 0
        assert r["invariants"]["errors"] == []

    def test_corruption_preset_guard_plane_end_to_end(
        self, tmp_path, monkeypatch
    ):
        """The result-integrity chaos preset (guard-plane acceptance):
        three resident-DEVICE-column corruptions — a zeroed capacity word,
        a NaN score input, a flipped pending bit on a RUNNING row — land
        mid-run while the host truth stays intact.  Every class must trip
        the sentinel, ZERO bad binds may dispatch (no duplicate acks, no
        accounting drift — condemned solves failed closed), the engaged
        fast path must demote AND re-promote after the cooldown, and the
        diagnostics bundle must --replay-bundle deterministically."""
        monkeypatch.setenv("KB_GUARD_DIR", str(tmp_path))
        r = run_preset("corruption", seed=0)
        g = r["guard"]
        assert g["corruptions_injected"] == 3
        assert g["trips_total"] >= 3
        assert g["failed_closed"] >= 3
        # zero bad binds across all injected corruption classes
        assert r["bind_integrity"]["duplicate_binds"] == 0
        assert r["invariants"]["errors"] == []
        # demotion engaged on trip; the half-open probe re-promoted
        topk = g["paths"]["topk"]
        assert topk["trips"] >= 1 and topk["promotions"] >= 1
        assert topk["state"] == "healthy"
        # every invariant above is what chaos_ok aggregates for the CLI
        assert g["chaos_ok"] is True
        # a self-contained bundle landed and reproduces the trip offline
        assert g["bundles"]
        from kube_batch_tpu.guard.bundle import replay_bundle

        rep = replay_bundle(g["bundles"][0])
        assert rep["reproduced"] is True
        assert rep["original_report"]["verdict"] >= 1

    def test_chaos_presets_are_seed_deterministic(self):
        """Same seed ⇒ byte-identical trace holds for the chaos machinery
        too (breaker paced by the virtual clock, tick-based resync)."""
        a = run_preset("brownout", seed=11)
        b = run_preset("brownout", seed=11)
        assert a["trace_sha256"] == b["trace_sha256"]

    def test_evict_recreates_controller_restores_pending_replica(self):
        """evict_recreates=True models a Job/ReplicaSet owner: the evicted
        replica reincarnates Pending (fresh uid) instead of vanishing, and
        stays a member of its job."""
        from kube_batch_tpu.sim.workload import fixed_gangs

        arrivals = fixed_gangs(t=0.5, n_gangs=1, gang_size=4, cpu=4000.0,
                               mem=2**30, duration=300.0, queues=("q0",),
                               name_prefix="low")
        arrivals[0].data["min_member"] = 2
        high = fixed_gangs(t=5.0, n_gangs=1, gang_size=1, cpu=4000.0,
                           mem=2**30, duration=300.0, queues=("q0",),
                           name_prefix="high")
        for e in high:
            for t in e.data["tasks"]:
                t["priority"] = 1000
        cfg = SimConfig(
            seed=0, n_nodes=1, node_cpu=16000.0, queues=(("q0", 1),),
            cycles=12, n_jobs=0, arrivals=arrivals + high,
            evict_recreates=True,
        )
        runner = SimRunner(cfg)
        r = runner.run()
        assert r["evictions"] >= 1
        assert r["invariants"]["errors"] == []
        # every low replica is still a member of its job, and at least one
        # carries a reincarnated uid (-r1+) from the recreation branch
        low_keys = runner.job_tasks["sim/low000"]
        assert len(low_keys) == 4
        reincarnated = [k for k in low_keys
                        if k in runner.cache.pods
                        and not runner.cache.pods[k].uid.endswith("-r0")]
        assert reincarnated, "no evicted replica was recreated"
