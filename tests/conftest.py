"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware isn't available in CI; sharding correctness is
validated on a virtual 8-device CPU backend (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip). Env must be set
before jax initializes, hence module scope here.
"""

import os
import sys

# Force-override: the image exports JAX_PLATFORMS=axon (the real-TPU tunnel);
# tests must run on the virtual 8-device CPU backend deterministically, and
# with a wedged axon tunnel backend init hangs at first dispatch unless
# PALLAS_AXON_POOL_IPS is cleared before jax import.
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from kube_batch_tpu.envutil import apply_hardened_cpu_env, deregister_axon_backend  # noqa: E402

# Honor a developer-supplied device count (e.g. XLA_FLAGS=...count=2 pytest
# to reproduce a 2-device sharding bug); default to the 8-device mesh.
_has_count = "--xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", "")
apply_hardened_cpu_env(n_devices=None if _has_count else 8)
# sitecustomize already ran (before conftest) — if the shell env had the axon
# pool configured, the factory is registered and must be popped before jax's
# first backend init or a wedged tunnel hangs even CPU work.
deregister_axon_backend()

import tempfile  # noqa: E402

# Flight-recorder dumps (obs/recorder.py) triggered by tests — budget
# sheds, guard trips, duplicate binds — must never land in the checkout;
# route them to a per-session temp dir unless a test overrides the knob.
os.environ.setdefault(
    "KB_TRACE_DIR", tempfile.mkdtemp(prefix="kb-flight-test-")
)

import pytest  # noqa: E402

# Run the whole suite under the lockdep runtime lock-order validator (the
# `go test -race` analog, kube_batch_tpu/analysis/lockdep.py): instrumented
# locks in cache/, cmd/server, k8s/watch and metrics/ record the
# acquisition-order graph while the ordinary tests execute; inversions or
# blocking-under-lock fail the run. Disable with KBT_LOCKDEP=0.
pytest_plugins = ["kube_batch_tpu.analysis.pytest_plugin"]


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: scale tests (seconds-long solves); always run in CI"
    )


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    return jax.devices()
