"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware isn't available in CI; sharding correctness is
validated on a virtual 8-device CPU backend (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip). Env must be set
before jax initializes, hence module scope here.
"""

import os

# Force-override: the image exports JAX_PLATFORMS=axon (the real-TPU tunnel);
# tests must run on the virtual 8-device CPU backend deterministically.
# If the axon tunnel is wedged (backend init hangs at import), run pytest with
# PALLAS_AXON_POOL_IPS= (empty) so sitecustomize skips axon registration.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    return jax.devices()
