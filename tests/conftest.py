"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware isn't available in CI; sharding correctness is
validated on a virtual 8-device CPU backend (the driver separately dry-runs
the multi-chip path via __graft_entry__.dryrun_multichip). Env must be set
before jax initializes, hence module scope here.
"""

import os
import sys

# Force-override: the image exports JAX_PLATFORMS=axon (the real-TPU tunnel);
# tests must run on the virtual 8-device CPU backend deterministically, and
# with a wedged axon tunnel backend init hangs at first dispatch unless
# PALLAS_AXON_POOL_IPS is cleared before jax import.
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from kube_batch_tpu.envutil import apply_hardened_cpu_env, deregister_axon_backend  # noqa: E402

# Honor a developer-supplied device count (e.g. XLA_FLAGS=...count=2 pytest
# to reproduce a 2-device sharding bug); default to the 8-device mesh.
_has_count = "--xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", "")
apply_hardened_cpu_env(n_devices=None if _has_count else 8)
# sitecustomize already ran (before conftest) — if the shell env had the axon
# pool configured, the factory is registered and must be popped before jax's
# first backend init or a wedged tunnel hangs even CPU work.
deregister_axon_backend()

import tempfile  # noqa: E402

# Flight-recorder dumps (obs/recorder.py) triggered by tests — budget
# sheds, guard trips, duplicate binds — must never land in the checkout;
# route them to a per-session temp dir unless a test overrides the knob.
os.environ.setdefault(
    "KB_TRACE_DIR", tempfile.mkdtemp(prefix="kb-flight-test-")
)

import pytest  # noqa: E402

# Run the whole suite under the lockdep runtime lock-order validator (the
# `go test -race` analog, kube_batch_tpu/analysis/lockdep.py): instrumented
# locks in cache/, cmd/server, k8s/watch and metrics/ record the
# acquisition-order graph while the ordinary tests execute; inversions or
# blocking-under-lock fail the run. Disable with KBT_LOCKDEP=0.
pytest_plugins = ["kube_batch_tpu.analysis.pytest_plugin"]


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: scale tests (seconds-long solves); always run in CI"
    )


@pytest.fixture(autouse=True)
def _no_thread_leaks(monkeypatch):
    """Worker-shutdown discipline (kbt tier D's runtime sibling): no NEW
    non-daemon thread may survive a test.  Every worker this codebase
    starts — writeback pool, status/dispatch pools, batcher, publisher
    encode, follower pull, prewarm, admin-http — has a bounded join on its
    shutdown path; the assert below verifies those joins actually reap
    everything.  Caches and schedulers the test constructed but never
    stopped are reaped here first (their stop()/close() are idempotent, so
    tests that do shut down pay nothing) — the discipline this fixture
    enforces is "every worker's owner has a working bounded join", not
    "every test calls stop()".  Daemon threads are exempt (they cannot
    block interpreter exit), and a short grace window absorbs workers that
    are mid-exit when the test body returns."""
    import threading
    import weakref
    import time as _time

    from kube_batch_tpu.cache.cache import SchedulerCache
    from kube_batch_tpu.scheduler import Scheduler

    caches, scheds = [], []
    orig_cache_init = SchedulerCache.__init__
    orig_sched_init = Scheduler.__init__

    def _cache_init(self, *a, **kw):
        orig_cache_init(self, *a, **kw)
        caches.append(weakref.ref(self))

    def _sched_init(self, *a, **kw):
        orig_sched_init(self, *a, **kw)
        scheds.append(weakref.ref(self))

    monkeypatch.setattr(SchedulerCache, "__init__", _cache_init)
    monkeypatch.setattr(Scheduler, "__init__", _sched_init)

    before = set(threading.enumerate())
    yield
    # reap schedulers before caches: a draining writeback may still
    # dispatch binds through the cache's pools
    for ref in scheds:
        s = ref()
        if s is not None:
            try:
                s.close()
            except Exception:
                pass  # the leak assert below still catches unreaped threads
    for ref in caches:
        c = ref()
        if c is not None:
            try:
                c.stop()
            except Exception:
                pass
    deadline = _time.monotonic() + 2.0
    leaked = []
    while True:
        leaked = [
            t for t in threading.enumerate()
            if t.is_alive() and not t.daemon and t not in before
        ]
        if not leaked or _time.monotonic() > deadline:
            break
        _time.sleep(0.05)
    assert not leaked, (
        "non-daemon thread(s) leaked by this test: "
        f"{sorted(t.name for t in leaked)} — every worker must be joined "
        "(bounded) on the owning object's stop()/close()"
    )


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    return jax.devices()
