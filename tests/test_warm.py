"""Warm-started incremental allocate (KB_WARM, ISSUE 14): the carried
cross-cycle candidate table + in-program repair must be bit-identical to
the KB_WARM=0 cold per-solve build (and therefore to the KB_TOPK=0 full
program) over randomized multi-cycle churn on all three impls
(single-device, shard_map, pjit); the merge/θ-cut/erosion fixtures pin the
table-refresh algebra at the solve level; the guard plane demotes the warm
path like any other fast path and half-open probes re-promote it; and the
carried table is dropped wholesale on axis growth, mesh changes, and
resident-cache drops (the plan_topk_bucket lifetime satellite).

The conftest forces an 8-device virtual CPU mesh (like test_shard_map);
sharded cases pad past SHARD_MIN_NODES so allocate dispatches sharded.
"""

from __future__ import annotations

import itertools
import os

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.framework.interface import get_action
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu.testing.synthetic import synthetic_cluster

_ENV_KEYS = ("KB_TOPK", "KB_WARM", "KB_SHARD", "KB_SHARD_MAP",
             "KB_TASK_SHARDS")


@pytest.fixture
def _env_guard():
    saved = {k: os.environ.get(k) for k in _ENV_KEYS}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _churn(cache, rng, serial, namespace="warm"):
    """Seed-deterministic churn: complete one bound gang, add one gang."""
    from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Pod, PodGroup
    from kube_batch_tpu.api.types import PodPhase

    for uid, job in sorted(cache.jobs.items()):
        pods = [cache.pods.get(key) for key in sorted(job.tasks)]
        if pods and all(p is not None and p.node_name for p in pods):
            for p in pods:
                cache.delete_pod(p)
            cache.delete_pod_group(uid)
            break
    j = next(serial)
    cache.add_pod_group(PodGroup(
        name=f"wm{j}", namespace=namespace, min_member=2,
        queue=f"q{j % 2}", creation_index=30_000 + j,
    ))
    for t in range(2):
        cache.add_pod(Pod(
            name=f"wm{j}-{t}", namespace=namespace,
            requests={"cpu": float(rng.choice([250.0, 500.0, 1000.0])),
                      "memory": float(2 ** 30)},
            annotations={GROUP_NAME_ANNOTATION: f"wm{j}"},
            phase=PodPhase.PENDING,
            creation_index=(30_000 + j) * 10 + t,
        ))


def _run_cycles(cache, conf, cycles=6, seed=11):
    rng = np.random.default_rng(seed)
    serial = itertools.count(1)
    binds = []
    warm_cycles = 0
    merge_cycles = 0
    partial_rerank = 0
    for _ in range(cycles):
        _churn(cache, rng, serial)
        ssn = open_session(cache, conf.tiers)
        try:
            for name in conf.actions:
                get_action(name).execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()
        lw = get_action("allocate").last_warm
        if lw is not None:
            warm_cycles += 1
            if not lw["cold"]:
                merge_cycles += 1
                if lw["reranked"] < lw["bucket_live"]:
                    partial_rerank += 1
        binds.append(sorted(cache.binder.binds.items()))
    cols = cache.columns
    status = sorted(
        (cols.task_by_row[r]._key, int(cols.t_status[r]))
        for r in np.flatnonzero(cols.t_valid).tolist()
    )
    return binds, status, warm_cycles, merge_cycles, partial_rerank


def _mk_cache(n_tasks=600, n_nodes=48, seed=0):
    return synthetic_cluster(
        n_tasks=n_tasks, n_nodes=n_nodes, gang_size=4, n_queues=2, seed=seed
    )


# --------------------------------------------------------------------------
# cycle-level warm-vs-cold equivalence over randomized churn (3 impls)
# --------------------------------------------------------------------------


def test_cycles_warm_vs_cold_single_device(_env_guard):
    """Identical churn, KB_WARM default (carried table) vs KB_WARM=0 (cold
    per-solve build): binds and end state must be identical; the carry
    must actually engage, take the merge path, and genuinely re-rank less
    than the live bucket (otherwise "warm" is just a renamed cold build)."""
    conf = load_scheduler_conf(None)
    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    os.environ["KB_SHARD"] = "0"

    # a CONTENDED cluster (standing ~60-row backlog): carried rows exist
    # across cycles, so the merge path can genuinely skip re-ranking them
    binds_w, status_w, wc, mc, partial = _run_cycles(
        _mk_cache(n_tasks=760, n_nodes=36), conf)
    assert wc > 0, "warm carry never engaged"
    assert mc > 0, "warm carry never took the merge path"
    assert partial > 0, "merge cycles always re-ranked the whole bucket"

    os.environ["KB_WARM"] = "0"
    binds_c, status_c, wc_c, _, _ = _run_cycles(
        _mk_cache(n_tasks=760, n_nodes=36), conf)
    assert wc_c == 0

    assert binds_w == binds_c, "warm vs cold binds diverged"
    assert status_w == status_c


@pytest.mark.parametrize("impl_env", [{}, {"KB_SHARD_MAP": "0"}])
def test_cycles_warm_sharded_vs_cold(_env_guard, impl_env):
    """The sharded carried table (shard_map default, pjit via
    KB_SHARD_MAP=0) against the cold sharded build under the same churn —
    bit-identical binds and end state."""
    conf = load_scheduler_conf(None)
    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    os.environ.update(impl_env)

    binds_w, status_w, wc, mc, _ = _run_cycles(
        _mk_cache(n_tasks=600, n_nodes=200), conf)
    assert get_action("allocate").last_solve_mode == "sharded"
    assert wc > 0 and mc > 0, "sharded warm carry never engaged/merged"

    os.environ["KB_WARM"] = "0"
    binds_c, status_c, wc_c, _, _ = _run_cycles(
        _mk_cache(n_tasks=600, n_nodes=200), conf)
    assert wc_c == 0

    assert binds_w == binds_c, (
        f"sharded warm vs cold binds diverged ({impl_env or 'shard_map'})")
    assert status_w == status_c


# --------------------------------------------------------------------------
# solve-level: the table-refresh algebra (merge, θ-cut, erosion, re-rank)
# --------------------------------------------------------------------------


def _session_snapshot(n_tasks, n_nodes, seed=3):
    cache = synthetic_cluster(
        n_tasks=n_tasks, n_nodes=n_nodes, gang_size=2, n_queues=2, seed=seed
    )
    conf = load_scheduler_conf(None)
    ssn = open_session(cache, conf.tiers)
    try:
        from kube_batch_tpu.actions.allocate import (
            build_session_snapshot,
            session_allocate_config,
        )

        snap, _meta = build_session_snapshot(ssn)
        config = session_allocate_config(ssn)
    finally:
        close_session(ssn)
    return snap, config


def _pend_rows(snap, bucket):
    rows = np.flatnonzero(np.asarray(snap.task_pending))
    assert 0 < rows.size <= bucket
    out = np.full(bucket, -1, np.int32)
    out[: rows.size] = rows.astype(np.int32)
    return out


def _zero_table(P, W):
    import jax.numpy as jnp

    return (jnp.zeros((P, W), jnp.int32),
            jnp.full((P, W), -(2 ** 31), jnp.int32),
            jnp.full((P, W), -1, jnp.int32),
            jnp.zeros(P, bool))


def _plan(P, row_map=None, changed=(), rerank_rows=None, rerank_slots=None,
          c_slots=8, r_slots=8):
    rm = np.full(P, -1, np.int32) if row_map is None else row_map
    ch = np.full(c_slots, -1, np.int32)
    ch[: len(changed)] = np.asarray(list(changed), np.int32)
    rr = np.full(r_slots, -1, np.int32)
    rs = np.full(r_slots, -1, np.int32)
    if rerank_rows is not None:
        rr[: len(rerank_rows)] = np.asarray(rerank_rows, np.int32)
        rs[: len(rerank_slots)] = np.asarray(rerank_slots, np.int32)
    return (rm, ch, rr, rs)


def _cmp(full, got, tag):
    for name in full._fields:
        if name.startswith("topk_"):
            continue
        assert np.array_equal(getattr(full, name), getattr(got, name)), (
            f"{tag}: diverged on {name}")


def test_warm_solve_carry_merge_and_cut_bit_exact():
    """The full solve-level life of a carried table: cold build → identity
    carry → displacement merge (a node's key improves and must enter) →
    hard erosion (a table node's budget zeroed: its entries are removed
    and the θ-cut must not resurrect anything) — each step bit-identical
    to the full-matrix AND the cold compacted solve on that snapshot."""
    import jax
    import jax.numpy as jnp

    from kube_batch_tpu.ops.assignment import (
        allocate_solve,
        allocate_topk_solve,
        warm_allocate_solve,
    )

    snap, config = _session_snapshot(400, 16, seed=7)
    P, K, W = 512, 4, 8
    rows = _pend_rows(snap, P)
    cfg_w = config._replace(topk=W)
    full = jax.device_get(allocate_solve(snap, config))
    cold = jax.device_get(
        allocate_topk_solve(snap, rows, config._replace(topk=K)))
    _cmp(full, cold, "cold-topk")

    # cold build through the warm program (everything re-ranked)
    live = int((rows >= 0).sum())
    plan0 = _plan(P, rerank_rows=rows[:live],
                  rerank_slots=np.arange(live), r_slots=P)
    res, table, _ = warm_allocate_solve(
        snap, jnp.asarray(rows), _zero_table(P, W), plan0, cfg_w, K)
    _cmp(full, jax.device_get(res), "warm-cold-build")

    # identity carry: nothing changed → no re-rank, no changed nodes
    ident = _plan(P, row_map=np.arange(P, dtype=np.int32))
    res, table, _ = warm_allocate_solve(
        snap, jnp.asarray(rows), table, ident, cfg_w, K)
    _cmp(full, jax.device_get(res), "warm-identity-carry")

    # displacement: free half of node 3's used capacity — its score rises
    # and the merge must insert it exactly where the full argmax would
    ni = np.asarray(snap.node_idle).copy()
    nu = np.asarray(snap.node_used).copy()
    freed = nu[3] * 0.5
    ni[3] += freed
    nu[3] -= freed
    snap2 = snap._replace(node_idle=jnp.asarray(ni),
                          node_used=jnp.asarray(nu))
    full2 = jax.device_get(allocate_solve(snap2, config))
    res, table, _ = warm_allocate_solve(
        snap2, jnp.asarray(rows), table,
        _plan(P, row_map=np.arange(P, dtype=np.int32), changed=[3]),
        cfg_w, K)
    _cmp(full2, jax.device_get(res), "warm-displacement-merge")

    # erosion: zero node 3's idle — carried entries for it are removed,
    # the θ-cut keeps the remainder an exact prefix
    ni3 = np.asarray(snap2.node_idle).copy()
    nu3 = np.asarray(snap2.node_used).copy()
    nu3[3] += ni3[3]
    ni3[3] = 0.0
    snap3 = snap2._replace(node_idle=jnp.asarray(ni3),
                           node_used=jnp.asarray(nu3))
    full3 = jax.device_get(allocate_solve(snap3, config))
    res, table, _ = warm_allocate_solve(
        snap3, jnp.asarray(rows), table,
        _plan(P, row_map=np.arange(P, dtype=np.int32), changed=[3]),
        cfg_w, K)
    _cmp(full3, jax.device_get(res), "warm-erosion-cut")


def test_warm_erosion_flags_rows_for_rerank():
    """A W=2 table whose best node dies must flag the affected rows as
    eroded (truncated AND valid prefix below k_min) — the signal the host
    planner re-ranks on next cycle — while staying bit-exact."""
    import jax
    import jax.numpy as jnp

    from kube_batch_tpu.ops.assignment import (
        allocate_solve,
        warm_allocate_solve,
    )

    # 16 nodes against W=2 tables: rows are TRUNCATED at build (feasible
    # nodes beyond the stored width exist), so losing a table head is a
    # genuine coverage loss the erosion flag must report
    snap, config = _session_snapshot(400, 16, seed=5)
    P, K, W = 512, 2, 2
    rows = _pend_rows(snap, P)
    cfg_w = config._replace(topk=W)
    live = int((rows >= 0).sum())
    plan0 = _plan(P, rerank_rows=rows[:live],
                  rerank_slots=np.arange(live), r_slots=P)
    _res, table, eroded0 = warm_allocate_solve(
        snap, jnp.asarray(rows), _zero_table(P, W), plan0, cfg_w, K)
    # live rows healthy after the build (padding slots flag eroded by
    # design — they carry empty always-truncated tables the planner
    # never maps to a task)
    assert not bool(np.any(np.asarray(eroded0)[:live]))

    # kill the most popular table node (mode of slot-0 indices)
    t_idx = np.asarray(table[0])
    top = np.bincount(t_idx[:live, 0]).argmax()
    ni = np.asarray(snap.node_idle).copy()
    nu = np.asarray(snap.node_used).copy()
    nv = np.asarray(snap.node_sched).copy()
    nv[top] = False  # unschedulable → statically infeasible for everyone
    snap2 = snap._replace(node_sched=jnp.asarray(nv),
                          node_idle=jnp.asarray(ni),
                          node_used=jnp.asarray(nu))
    full2 = jax.device_get(allocate_solve(snap2, config))
    res, _table, eroded = warm_allocate_solve(
        snap2, jnp.asarray(rows), table,
        _plan(P, row_map=np.arange(P, dtype=np.int32), changed=[int(top)]),
        cfg_w, K)
    _cmp(full2, jax.device_get(res), "erosion-fixture")
    live_rows = rows[:live]
    assert bool(np.any(np.asarray(eroded)[:live][live_rows >= 0])), (
        "no row flagged eroded after its table head died")


def test_warm_task_invalidation_rerank_bit_exact():
    """A row whose OWN features change (its request grows) is re-ranked by
    the planner; the warm program with that row in the rerank sub-bucket
    must match the full solve on the mutated snapshot."""
    import jax
    import jax.numpy as jnp

    from kube_batch_tpu.ops.assignment import (
        allocate_solve,
        warm_allocate_solve,
    )

    snap, config = _session_snapshot(400, 16, seed=9)
    P, K, W = 512, 4, 8
    rows = _pend_rows(snap, P)
    cfg_w = config._replace(topk=W)
    live = int((rows >= 0).sum())
    plan0 = _plan(P, rerank_rows=rows[:live],
                  rerank_slots=np.arange(live), r_slots=P)
    _res, table, _ = warm_allocate_solve(
        snap, jnp.asarray(rows), _zero_table(P, W), plan0, cfg_w, K)

    victim_slot = live // 2
    victim_row = int(rows[victim_slot])
    req = np.asarray(snap.task_req).copy()
    req[victim_row] *= 2.0
    snap2 = snap._replace(task_req=jnp.asarray(req))
    full2 = jax.device_get(allocate_solve(snap2, config))
    res, _t, _ = warm_allocate_solve(
        snap2, jnp.asarray(rows), table,
        _plan(P, row_map=np.arange(P, dtype=np.int32),
              rerank_rows=[victim_row], rerank_slots=[victim_slot]),
        cfg_w, K)
    _cmp(full2, jax.device_get(res), "task-invalidation-rerank")


# --------------------------------------------------------------------------
# guard plane: warm demotes like any fast path, half-open re-promotes
# --------------------------------------------------------------------------


def test_guard_demotes_warm_and_repromotes(_env_guard):
    """A trip attributed to the warm path pins the dispatch to the cold
    build (last_warm None, compaction still engaged); after the cooldown's
    clean cycles the half-open probe runs warm again and one clean engaged
    cycle re-promotes."""
    from kube_batch_tpu.guard import guard_of

    conf = load_scheduler_conf(None)
    for k in _ENV_KEYS:
        os.environ.pop(k, None)
    os.environ["KB_SHARD"] = "0"
    cache = _mk_cache()
    rng = np.random.default_rng(23)
    serial = itertools.count(1)
    gp = guard_of(cache)
    gp.cooldown = 2

    def cycle():
        _churn(cache, rng, serial)
        ssn = open_session(cache, conf.tiers)
        try:
            for name in conf.actions:
                get_action(name).execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()
        gp.end_cycle()

    for _ in range(3):
        cycle()
    assert get_action("allocate").last_warm is not None

    gp.trip("allocate", ["warm"], reason="test", detail="forced")
    assert gp.paths["warm"].state == "demoted"
    cycle()
    assert get_action("allocate").last_warm is None, (
        "demoted warm path still dispatched the carry")
    assert get_action("allocate").last_topk is not None, (
        "warm demotion must not take compaction down with it")
    while gp.paths["warm"].state == "demoted":
        cycle()
    assert gp.paths["warm"].state == "probing"
    cycle()  # the half-open probe runs warm and promotes on the clean cycle
    assert get_action("allocate").last_warm is not None
    assert gp.paths["warm"].state == "healthy"
    assert gp.paths["warm"].promotions >= 1


# --------------------------------------------------------------------------
# table lifetime: axis growth / resident drops / mesh changes drop wholesale
# --------------------------------------------------------------------------


def test_warm_table_dropped_on_axis_growth_and_resident_drop(_env_guard):
    """The plan_topk_bucket lifetime satellite: a cache axis re-grow
    (ColumnStore.reserve) and a resident drop (guard heal) must invalidate
    the carried table WHOLESALE, never index-shift it."""
    cache = _mk_cache()
    cols = cache.columns
    st = cols.warm_table_state(mesh=None, impl=None)
    assert cols.warm_table_state(mesh=None, impl=None) is st
    cols.reserve(n_tasks=cols.tasks.cap + 1)       # task-axis growth
    assert not cols._warm_tables, "task growth kept the carried table"

    st = cols.warm_table_state(mesh=None, impl=None)
    cols.reserve(n_nodes=cols.nodes.cap + 1)       # node-axis growth
    assert not cols._warm_tables, "node growth kept the carried table"

    st = cols.warm_table_state(mesh=None, impl=None)
    cols.drop_resident()                           # guard heal path
    assert not cols._warm_tables, "drop_resident kept the carried table"
    assert st is not cols.warm_table_state(mesh=None, impl=None)


def test_warm_table_dropped_on_mesh_change(_env_guard):
    """A mesh change drops the old mesh's resident cache AND its carried
    tables — stale node placements must never feed a warm merge."""
    import jax

    from kube_batch_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device (forced-host) backend")
    conf = load_scheduler_conf(None)
    cache = _mk_cache(n_tasks=200, n_nodes=16)
    ssn = open_session(cache, conf.tiers)
    try:
        from kube_batch_tpu.actions.allocate import build_session_snapshot

        snap, _ = build_session_snapshot(ssn)
        cols = cache.columns
        mesh = make_mesh(2)
        cols.per_cycle_resident(snap, mesh=mesh)
        st = cols.warm_table_state(mesh=mesh, impl="shard_map")
        assert (mesh, "shard_map") in cols._warm_tables
        # path flip: the single-device dispatch creates its cache and the
        # abandoned mesh's residency + carried tables go with it
        cols.per_cycle_resident(snap, mesh=None)
        assert (mesh, "shard_map") not in cols._warm_tables
        del st
    finally:
        close_session(ssn)


def test_warm_declines_without_absorbed_delta(_env_guard):
    """A state that has not absorbed the current resident swap (broken
    delta chain — e.g. KB_DEVICE_CACHE=0) must refuse to plan; the
    dispatch then falls back to the cold build."""
    cache = _mk_cache(n_tasks=200, n_nodes=16)
    cols = cache.columns
    st = cols.warm_table_state(mesh=None, impl=None)
    rows = np.full(64, -1, np.int32)
    rows[:4] = [0, 1, 2, 3]
    from kube_batch_tpu.ops.assignment import AllocateConfig

    assert st.plan(cols, rows, 4, AllocateConfig()) is None


# --------------------------------------------------------------------------
# satellite: the bucketed failure histogram
# --------------------------------------------------------------------------


def test_failure_histogram_bucket_matches_full():
    """failure_histogram_bucket_solve == failure_histogram_solve at every
    bucket row (the only rows any consumer reads), single-device and over
    a forced mesh (shard_map + pjit)."""
    import jax
    import jax.numpy as jnp

    from kube_batch_tpu.ops.assignment import (
        failure_histogram_bucket_solve,
        failure_histogram_solve,
    )

    snap, _config = _session_snapshot(240, 8, seed=13)
    rows = _pend_rows(snap, 256)
    live = rows[rows >= 0]
    hf = np.asarray(failure_histogram_solve(snap))
    hb = np.asarray(failure_histogram_bucket_solve(snap, jnp.asarray(rows)))
    assert np.array_equal(hf[live], hb[live])
    assert not hb[[r for r in range(hb.shape[0])
                   if r not in set(live.tolist())]].any()

    if len(jax.devices()) >= 4:
        from kube_batch_tpu.parallel.mesh import (
            failure_histogram_bucket_fn,
            make_mesh,
        )

        mesh = make_mesh(4)
        with mesh:
            hs = np.asarray(
                failure_histogram_bucket_fn(mesh, impl="shard_map")(
                    snap, jnp.asarray(rows)))
            hp = np.asarray(
                failure_histogram_bucket_fn(mesh, impl="pjit")(
                    snap, jnp.asarray(rows)))
        assert np.array_equal(hf[live], hs[live])
        assert np.array_equal(hf[live], hp[live])


# --------------------------------------------------------------------------
# knob parsing
# --------------------------------------------------------------------------


def test_resolve_warm_knob(_env_guard):
    from kube_batch_tpu.actions.allocate import resolve_warm

    os.environ.pop("KB_WARM", None)
    assert resolve_warm() is True
    os.environ["KB_WARM"] = "0"
    assert resolve_warm() is False
    os.environ["KB_WARM"] = "1"
    assert resolve_warm() is True
    # garbage DISABLES — a typo'd disable attempt must not silently
    # re-enable the fast path under an oracle comparison (KB_TOPK rule)
    os.environ["KB_WARM"] = "offf"
    assert resolve_warm() is False
