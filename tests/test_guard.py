"""Result-integrity guard plane: the fused invariant sentinel condemns
corrupted solves (fail closed — zero binds), the per-fast-path breaker
demotes/probes/re-promotes without wedging or flapping, trips survive the
races (in-flight audit, mid-cycle conf reload), and the diagnostics bundle
replays deterministically."""

from __future__ import annotations

import threading

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.pod import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    Queue,
)
from kube_batch_tpu.api.types import PodPhase, TaskStatus
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.framework.conf import load_scheduler_conf, shipped_conf_path
from kube_batch_tpu.framework.interface import get_action
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu.guard.plane import DEMOTED, HEALTHY, PROBING, GuardPlane
from kube_batch_tpu.sim import kubelet as kl
from kube_batch_tpu.testing.synthetic import GiB

# the SHIPPED 5-action conf: a fail-closed cycle writes the unplaced job
# back PodGroupPending, and only the enqueue action re-promotes it next
# cycle — the production pipeline is the recovery path under test
CONF = load_scheduler_conf(shipped_conf_path())


def _mk_cache(reserve_topk=False):
    cache = SchedulerCache()
    if reserve_topk:
        # capT ≥ 1024 gives the KB_TOPK plan a 256-row pending bucket and
        # capN 64 > K, so the compacted fast path ENGAGES at test scale
        cache.columns.reserve(n_tasks=1024, n_nodes=64)
    cache.add_queue(Queue(name="q0", uid="uq0", weight=1))
    for i in range(4):
        cache.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": 8000.0, "memory": 64 * GiB, "pods": 110.0},
        ))
    return cache


def _add_gang(cache, serial, size=2, cpu=500.0):
    g = f"g{serial}"
    cache.add_pod_group(PodGroup(
        name=g, namespace="t", uid=f"pg-{g}", min_member=size, queue="q0",
        creation_index=serial,
    ))
    for k in range(size):
        cache.add_pod(Pod(
            name=f"{g}-{k}", namespace="t", uid=f"pod-{g}-{k}",
            requests={"cpu": cpu, "memory": 1 * GiB},
            annotations={GROUP_NAME_ANNOTATION: g},
            phase=PodPhase.PENDING, creation_index=serial * 100 + k,
        ))


def _cycle(cache):
    ssn = open_session(cache, CONF.tiers)
    ssn.action_names = list(CONF.actions)
    try:
        for name in CONF.actions:
            get_action(name).execute(ssn)
    finally:
        close_session(ssn)
    cache.flush_binds()
    gp = getattr(cache, "guard_plane", None)
    if gp is not None:
        gp.end_cycle()  # what Scheduler._cycle does each tick


def _corrupt_ledger(cache):
    """Zero a live node's capacity word in the STATIC device feature cache
    — the sim corruption preset's 'ledger' class, inlined."""
    import jax

    cols = cache.columns
    feat = cols._dev_cache[None]
    ver, dev = feat["node_alloc"]
    host = np.array(jax.device_get(dev))
    live = np.flatnonzero(np.asarray(cols.n_valid))
    host[int(live[0])] = 0.0
    feat["node_alloc"] = (ver, jax.device_put(host))


def _corrupt_pending(cache):
    """Flip a RUNNING row's device pending bit, mirror pinned to host truth
    (the HBM-flip model) — detected by the host eligibility checksum."""
    import jax

    cols = cache.columns
    rc = cols._per_cycle_dev[None]
    rows = np.flatnonzero(
        np.asarray(cols.t_status) == int(TaskStatus.RUNNING)
    )
    r = int(rows[0])
    host = np.array(jax.device_get(rc._dev["task_pending"]))
    host[r] = True
    rc._dev["task_pending"] = jax.device_put(host)
    rc._mirror["task_pending"][r] = False
    return r


# ==========================================================================
# tier 1: the fused sentinel + fail-closed dispatch
# ==========================================================================


class TestSentinelFailClosed:
    def test_clean_cycles_never_trip(self):
        cache = _mk_cache(reserve_topk=True)
        for s in range(3):
            _add_gang(cache, s)
            _cycle(cache)
        gp = cache.guard_plane
        assert gp.enabled and gp.trips_total == 0
        assert len(cache.binder.binds) == 6

    def test_corrupted_capacity_word_fails_closed_then_heals(self, tmp_path):
        cache = _mk_cache(reserve_topk=True)
        _add_gang(cache, 0)
        _cycle(cache)
        gp = cache.guard_plane
        gp.bundle_dir = str(tmp_path)
        binds_before = len(cache.binder.binds)
        _corrupt_ledger(cache)
        _add_gang(cache, 1)
        _cycle(cache)
        # condemned solve: the sentinel's capacity cross-check fired and
        # NOTHING was dispatched from it
        assert gp.trips_total >= 1
        assert gp.failed_closed >= 1
        assert len(cache.binder.binds) == binds_before
        assert any("node_overcommit" in t["detail"] for t in gp.trip_log)
        # the trip healed the resident caches (drop + full re-upload), so
        # the NEXT cycle is clean and the gang binds
        _cycle(cache)
        assert len(cache.binder.binds) == binds_before + 2
        assert gp.trips_total == 1  # no re-trip after the heal

    def test_phantom_pending_bit_caught_by_host_checksum(self, tmp_path):
        cache = _mk_cache(reserve_topk=True)
        _add_gang(cache, 0)
        _cycle(cache)
        # progress gang 0 to RUNNING so a flippable row exists
        for key in sorted(cache.pods):
            pod = cache.pods[key]
            if pod.node_name:
                kl.set_running(cache, key, pod.node_name)
        _cycle(cache)
        gp = cache.guard_plane
        gp.bundle_dir = str(tmp_path)
        _corrupt_pending(cache)
        binds_before = len(cache.binder.binds)
        running = {k for k, p in cache.pods.items() if p.node_name}
        _add_gang(cache, 1)
        _cycle(cache)
        # the FIRST dispatch that consumed the corrupt column (reclaim runs
        # before allocate in the shipped conf) tripped on the checksum and
        # failed closed; its heal re-uploaded clean columns, so the SAME
        # cycle's later actions lawfully placed the new gang — the phantom
        # row itself was never re-dispatched
        assert gp.trips_total == 1
        assert any("eligibility" in t["detail"] for t in gp.trip_log)
        assert len(cache.binder.binds) == binds_before + 2
        for key in running:  # no RUNNING pod was re-bound anywhere
            assert cache.binder.binds[key] == cache.pods[key].node_name
        _cycle(cache)  # clean after the heal — no re-trip
        assert gp.trips_total == 1

    def test_kb_guard_escape_hatch_disables_everything(self, monkeypatch):
        monkeypatch.setenv("KB_GUARD", "0")
        cache = _mk_cache(reserve_topk=True)
        _add_gang(cache, 0)
        _cycle(cache)
        gp = cache.guard_plane
        assert not gp.enabled
        _corrupt_ledger(cache)
        _add_gang(cache, 1)
        _cycle(cache)  # no sentinel, no trip — the pre-guard behavior
        assert gp.trips_total == 0

    def test_sentinel_rides_the_existing_readback(self):
        """The guard adds ZERO extra device transfers on the allocate path:
        exactly one device_get per execute (the pre-guard count)."""
        import jax

        cache = _mk_cache(reserve_topk=True)
        _add_gang(cache, 0)
        _cycle(cache)  # warm
        _add_gang(cache, 1)
        calls = []
        real = jax.device_get

        def counting(x):
            calls.append(1)
            return real(x)

        ssn = open_session(cache, CONF.tiers)
        try:
            import unittest.mock as mock

            with mock.patch.object(
                type(get_action("allocate")), "execute",
                wraps=get_action("allocate").execute,
            ):
                with mock.patch("jax.device_get", side_effect=counting):
                    get_action("allocate").execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()
        # one choke-point readback (+ one fit-histogram readback only on
        # failure cycles — this cycle places everything)
        assert len(calls) == 1


# ==========================================================================
# tier 3: the per-fast-path breaker (demote → cooldown → probe → promote)
# ==========================================================================


class TestGuardPlaneBreaker:
    def _plane(self, cooldown=3):
        return GuardPlane(enabled=True, audit_every=0, cooldown=cooldown)

    def test_demote_probe_repromote_arc(self):
        gp = self._plane(cooldown=3)
        assert gp.allow("topk")
        gp.consume_verdict("allocate", ["topk"], 7)  # trip
        assert gp.paths["topk"].state == DEMOTED
        assert not gp.allow("topk")
        gp.end_cycle()  # the TRIP cycle itself — not a clean cycle
        for _ in range(3):  # clean oracle cycles
            gp.end_cycle()
        assert gp.paths["topk"].state == PROBING
        assert gp.allow("topk")  # half-open: the fast path runs again
        gp.consume_verdict("allocate", ["topk"], 0)  # clean engaged probe
        gp.end_cycle()
        assert gp.paths["topk"].state == HEALTHY
        assert gp.paths["topk"].promotions == 1

    def test_failed_probe_re_demotes_and_never_flaps_per_cycle(self):
        gp = self._plane(cooldown=2)
        gp.consume_verdict("allocate", ["topk"], 1)
        for _ in range(3):  # trip cycle + 2 clean
            gp.end_cycle()
        assert gp.paths["topk"].state == PROBING
        gp.consume_verdict("allocate", ["topk"], 1)  # probe fails
        assert gp.paths["topk"].state == DEMOTED
        gp.end_cycle()  # the failed-probe cycle
        gp.end_cycle()
        # the next probe window is a FULL cooldown away — no per-cycle flap
        assert gp.paths["topk"].state == DEMOTED
        gp.end_cycle()
        assert gp.paths["topk"].state == PROBING

    def test_unengaged_probe_waits_without_wedging(self):
        """A probing path that gets no engagement (no pending work for the
        compacted plan) must stay PROBING — allow() keeps answering True,
        so the next engageable cycle promotes; never permanently demoted."""
        gp = self._plane(cooldown=1)
        gp.consume_verdict("allocate", ["topk"], 1)
        gp.end_cycle()  # trip cycle
        gp.end_cycle()  # one clean cycle → half-open
        assert gp.paths["topk"].state == PROBING
        for _ in range(5):  # idle cycles: no engagement either way
            gp.end_cycle()
        assert gp.paths["topk"].state == PROBING
        assert gp.allow("topk")
        gp.consume_verdict("allocate", ["topk"], 0)
        gp.end_cycle()
        assert gp.paths["topk"].state == HEALTHY

    def test_unattributable_trip_demotes_engaged_history(self):
        gp = self._plane()
        gp.consume_verdict("allocate", ["topk"], 0)  # topk has engaged
        gp.consume_verdict("reclaim", [], 3)         # full-matrix trip
        assert gp.paths["topk"].state == DEMOTED
        assert gp.paths["shard_map"].state == HEALTHY  # never engaged

    def test_audit_mismatch_trips_and_demotes(self):
        gp = self._plane()
        gp.note_audit("allocate", ["shard_map"], matched=False,
                      detail="fast-vs-oracle mismatch")
        assert gp.paths["shard_map"].state == DEMOTED
        assert gp.audits_mismatched == 1
        assert any(t["reason"] == "audit" for t in gp.trip_log)

    def test_audit_cadence_counts_dispatches(self):
        gp = GuardPlane(enabled=True, audit_every=4, cooldown=2)
        due = [gp.audit_due("allocate") for _ in range(8)]
        assert due == [False, False, False, True, False, False, False, True]
        # per-action counters are independent
        assert gp.audit_due("reclaim") is False

    def test_trip_concurrent_with_inflight_audit_does_not_wedge(self):
        """The re-promotion race the ISSUE names: a sentinel trip lands
        while an audit of the same cycle is still comparing.  Whatever the
        interleaving, the path must end DEMOTED with a working cooldown —
        never wedged in a state allow()/end_cycle() cannot move."""
        for _ in range(20):
            gp = self._plane(cooldown=2)
            barrier = threading.Barrier(2)

            def sentinel_trip():
                barrier.wait()
                gp.consume_verdict("allocate", ["topk"], 5)

            def audit_mismatch():
                barrier.wait()
                gp.note_audit("allocate", ["topk"], matched=False)

            ts = [threading.Thread(target=sentinel_trip),
                  threading.Thread(target=audit_mismatch)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert gp.paths["topk"].state == DEMOTED
            gp.end_cycle()  # trip cycle
            for _ in range(2):
                gp.end_cycle()
            assert gp.paths["topk"].state == PROBING  # cooldown still works
            gp.consume_verdict("allocate", ["topk"], 0)
            gp.end_cycle()
            assert gp.paths["topk"].state == HEALTHY

    def test_mid_cycle_conf_reload_preserves_guard_state(self, tmp_path):
        """scheduler.py's hot reload keeps the RUNNING conf on a broken
        edit and swaps actions at the cycle boundary — either way the
        guard plane rides the CACHE, not the conf, so demotion state
        survives a reload mid-cooldown."""
        from kube_batch_tpu.scheduler import Scheduler

        cache = _mk_cache()
        conf_path = tmp_path / "conf.yaml"
        conf_path.write_text(
            'actions: "enqueue, allocate, backfill"\n'
            "tiers:\n- plugins:\n  - name: gang\n  - name: predicates\n"
            "  - name: proportion\n  - name: nodeorder\n"
        )
        sched = Scheduler(cache, conf_path=str(conf_path),
                          schedule_period=0.01)
        sched.pipelined = False
        _add_gang(cache, 0)
        sched.run_once()
        gp = cache.guard_plane
        gp.consume_verdict("allocate", ["topk"], 9)  # demote mid-run
        assert gp.paths["topk"].state == DEMOTED
        # conf edit lands mid-cooldown; next cycle hot-reloads it
        conf_path.write_text(
            'actions: "enqueue, allocate"\n'
            "tiers:\n- plugins:\n  - name: gang\n  - name: predicates\n"
            "  - name: proportion\n  - name: nodeorder\n"
        )
        import os

        os.utime(conf_path, (1e9, 2e9))  # force a visible mtime move
        sched.run_once()
        assert [a.name for a in sched.actions] == ["enqueue", "allocate"]
        assert cache.guard_plane is gp  # same breaker, same state machine
        assert gp.paths["topk"].state in (DEMOTED, PROBING)
        for _ in range(gp.cooldown + 1):
            sched.run_once()
        assert gp.paths["topk"].state == PROBING  # cooldown ran to half-open


# ==========================================================================
# demotion-aware dispatch: a demoted path really runs its oracle
# ==========================================================================


class TestDemotionAwareDispatch:
    def test_demoted_topk_runs_full_matrix_until_repromoted(self):
        from kube_batch_tpu.actions.allocate import (
            dispatch_allocate_solve,
            session_allocate_config,
        )
        from kube_batch_tpu.actions.allocate import build_session_snapshot

        cache = _mk_cache(reserve_topk=True)
        _add_gang(cache, 0)
        _cycle(cache)
        gp = cache.guard_plane
        alloc = get_action("allocate")
        assert alloc.last_topk is not None  # compaction engaged when healthy
        gp.paths["topk"].state = DEMOTED
        _add_gang(cache, 1)
        _cycle(cache)
        assert alloc.last_topk is None      # oracle (full-matrix) ran
        gp.paths["topk"].state = HEALTHY
        _add_gang(cache, 2)
        _cycle(cache)
        assert alloc.last_topk is not None  # fast path back
        # every cycle placed its gang regardless of path — demotion is a
        # performance decision, never a correctness one
        assert len(cache.binder.binds) == 6


# ==========================================================================
# diagnostics bundles: dump, atomicity, deterministic replay
# ==========================================================================


class TestBundles:
    def test_trip_bundle_replays_deterministically(self, tmp_path):
        cache = _mk_cache(reserve_topk=True)
        _add_gang(cache, 0)
        _cycle(cache)
        gp = cache.guard_plane
        gp.bundle_dir = str(tmp_path)
        _corrupt_ledger(cache)
        _add_gang(cache, 1)
        _cycle(cache)
        assert len(gp.bundles) >= 1
        from kube_batch_tpu.guard.bundle import load_bundle, replay_bundle

        path = gp.bundles[0]
        snap, meta, pend_rows = load_bundle(path)
        assert meta["action"] in ("allocate", "reclaim", "preempt",
                                  "backfill")
        assert meta["report"]["verdict"] > 0
        # the replay re-derives the SAME integrity failure from the
        # captured (corrupt) snapshot — twice, bit-stable
        rep1 = replay_bundle(path)
        rep2 = replay_bundle(path)
        assert rep1["reproduced"] and rep2["reproduced"]
        assert rep1["fast_verdict"] == rep2["fast_verdict"]
        assert rep1.get("fast_violations") == rep2.get("fast_violations")

    def test_checksum_trip_bundle_reproduces_via_host_checksum(
        self, tmp_path
    ):
        cache = _mk_cache(reserve_topk=True)
        _add_gang(cache, 0)
        _cycle(cache)
        for key in sorted(cache.pods):
            pod = cache.pods[key]
            if pod.node_name:
                kl.set_running(cache, key, pod.node_name)
        _cycle(cache)
        gp = cache.guard_plane
        gp.bundle_dir = str(tmp_path)
        _corrupt_pending(cache)
        _add_gang(cache, 1)
        _cycle(cache)
        assert gp.bundles
        from kube_batch_tpu.guard.bundle import replay_bundle

        rep = replay_bundle(gp.bundles[-1])
        assert rep["reproduced"]
        assert rep["host_checksum_mismatch"] is True

    def test_no_half_bundles_on_disk(self, tmp_path):
        cache = _mk_cache(reserve_topk=True)
        _add_gang(cache, 0)
        _cycle(cache)
        gp = cache.guard_plane
        gp.bundle_dir = str(tmp_path)
        _corrupt_ledger(cache)
        _add_gang(cache, 1)
        _cycle(cache)
        entries = sorted(p.name for p in tmp_path.iterdir())
        assert entries and all(e.startswith("trip-") for e in entries), (
            "atomic publish must leave only complete trip-* bundles"
        )


# ==========================================================================
# sentinel invariant math (device-level units)
# ==========================================================================


class TestInvariantMath:
    @pytest.fixture(scope="class")
    def snap(self):
        import jax.numpy as jnp

        from kube_batch_tpu.analysis.jaxpr_audit import abstract_snapshot
        from kube_batch_tpu.api.snapshot import DeviceSnapshot

        ab = abstract_snapshot()
        z = DeviceSnapshot(*[jnp.zeros(s.shape, s.dtype) for s in ab])
        T, R, N, J = 16, 3, 8, 4
        return z._replace(
            task_req=jnp.ones((T, R), jnp.float32),
            task_resreq=jnp.ones((T, R), jnp.float32),
            task_job=jnp.arange(T, dtype=jnp.int32) % J,
            task_valid=jnp.ones(T, bool),
            task_pending=jnp.ones(T, bool),
            task_node=jnp.full(T, -1, jnp.int32),
            task_aff_idx=jnp.full(1, -1, jnp.int32),
            task_pref_idx=jnp.full(1, -1, jnp.int32),
            node_idle=jnp.full((N, R), 8.0, jnp.float32),
            node_alloc=jnp.full((N, R), 8.0, jnp.float32),
            node_valid=jnp.ones(N, bool),
            node_sched=jnp.ones(N, bool),
            job_min_avail=jnp.ones(J, jnp.int32),
            job_valid=jnp.ones(J, bool),
            job_schedulable=jnp.ones(J, bool),
            queue_weight=jnp.ones(2, jnp.float32),
            queue_valid=jnp.ones(2, bool),
            total=jnp.full(R, 64.0, jnp.float32),
            quanta=jnp.full(R, 0.01, jnp.float32),
        )

    def test_lawful_result_verdict_zero(self, snap):
        from kube_batch_tpu.ops.assignment import AllocateConfig
        from kube_batch_tpu.ops.invariants import allocate_sentinel_solve

        _res, v, h, _e = allocate_sentinel_solve(snap, AllocateConfig())
        assert int(v) == 0 and not np.asarray(h).any()

    def test_nan_ledger_hits_nonfinite_slot(self, snap):
        import jax.numpy as jnp

        from kube_batch_tpu.ops.assignment import AllocateConfig
        from kube_batch_tpu.ops.invariants import (
            INVARIANT_NAMES,
            allocate_sentinel_solve,
        )

        bad = snap._replace(node_used=snap.node_used.at[0, 0].set(jnp.nan))
        _res, v, h = allocate_sentinel_solve(bad, AllocateConfig())[:3]
        assert int(v) > 0
        assert np.asarray(h)[INVARIANT_NAMES.index("nonfinite")] > 0

    def test_inconsistent_ledger_hits_overcommit_slot(self, snap):
        from kube_batch_tpu.ops.assignment import AllocateConfig
        from kube_batch_tpu.ops.invariants import (
            INVARIANT_NAMES,
            allocate_sentinel_solve,
        )

        bad = snap._replace(node_idle=snap.node_idle.at[0, 0].set(1e6))
        _res, v, h = allocate_sentinel_solve(bad, AllocateConfig())[:3]
        assert int(v) > 0
        assert np.asarray(h)[INVARIANT_NAMES.index("node_overcommit")] > 0

    def test_pipelined_occupancy_is_lawful(self, snap):
        """A node carrying a PIPELINED task lawfully shows used >
        allocatable by that task's resreq (it borrows the dying victim's
        share) — the capacity cross-check must NOT false-positive there."""
        import jax.numpy as jnp

        from kube_batch_tpu.api.types import TaskStatus
        from kube_batch_tpu.ops.assignment import AllocateConfig
        from kube_batch_tpu.ops.invariants import allocate_sentinel_solve

        s = snap._replace(
            task_status=snap.task_status.at[0].set(
                int(TaskStatus.PIPELINED)),
            task_node=snap.task_node.at[0].set(0),
            task_pending=snap.task_pending.at[0].set(False),
            # node 0: fully used + the pipelined borrow on top
            node_idle=snap.node_idle.at[0].set(0.0),
            node_used=snap.node_used.at[0].set(9.0),  # alloc 8 + borrow 1
        )
        _res, v, _h, _e = allocate_sentinel_solve(s, AllocateConfig())
        assert int(v) == 0

    def test_evict_sentinel_clean_and_checksum_stable(self, snap):
        from kube_batch_tpu.ops.eviction import EvictConfig
        from kube_batch_tpu.ops.invariants import (
            evict_sentinel_solve,
            host_eligibility_checksum,
        )

        _res, v, _h, e = evict_sentinel_solve(
            snap, EvictConfig(mode="reclaim"))
        assert int(v) == 0
        # the device checksum equals the host twin on an uncorrupted snap
        host_snap = snap  # jnp arrays read host-side via np.asarray
        assert (int(e) & 0xFFFFFFFF) == host_eligibility_checksum(host_snap)
