"""Heap ordering tests — analog of util/priority_queue_test.go."""

from kube_batch_tpu.utils.priority_queue import PriorityQueue


def test_orders_by_less_fn():
    pq = PriorityQueue(less=lambda a, b: a < b, items=[5, 1, 4, 2, 3])
    assert [pq.pop() for _ in range(len(pq))] == [1, 2, 3, 4, 5]


def test_ties_are_fifo():
    pq = PriorityQueue(less=lambda a, b: a[0] < b[0])
    for item in [(1, "a"), (1, "b"), (0, "c"), (1, "d")]:
        pq.push(item)
    assert [pq.pop() for _ in range(len(pq))] == [(0, "c"), (1, "a"), (1, "b"), (1, "d")]


def test_empty_and_len():
    pq = PriorityQueue(less=lambda a, b: a < b)
    assert pq.empty() and not pq
    pq.push(1)
    assert not pq.empty() and len(pq) == 1 and pq.peek() == 1
