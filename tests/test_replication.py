"""Replicated follower read plane (replicate/): KBR1 wire round-trips,
frozen-snapshot leader/follower bit-match, delta-chain application under
churn, staleness bounds, gap→full-resync escalation, warm restart
re-adoption, and the server-side /v1/whatif/sweep search.

The bit-match tests are the subsystem's contract: a follower that has
applied the leader's record for cycle N must answer /v1/whatif (and
/v1/whatif/sweep) BYTE-identically to the leader frozen at cycle N —
same verdict, same placement, same staleness block."""

from __future__ import annotations

import json

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.pod import Pod, PodGroup, Queue
from kube_batch_tpu.api.types import PodPhase
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.framework.interface import get_action
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu.replicate import stream
from kube_batch_tpu.replicate.follower import (
    FollowerApplier,
    FollowerCache,
    ReplicationFollower,
)
from kube_batch_tpu.replicate.publisher import ReplicationPublisher
from kube_batch_tpu.serve.plane import QueryPlane, WhatifError

from fixtures import GiB, build_cache, build_node, build_pod

CONF = load_scheduler_conf(None)


def _run(cache, names=("allocate",)):
    ssn = open_session(cache, CONF.tiers)
    try:
        for name in names:
            get_action(name).execute(ssn)
    finally:
        close_session(ssn)
    cache.flush_binds()


def _probe(qp: QueryPlane, body: dict) -> dict:
    fut = qp.submit(body)
    qp.batcher.tick(now=qp.batcher.clock.monotonic() + 1e6)
    return fut.result(timeout=60)


def _sweep(qp: QueryPlane, body: dict) -> dict:
    fut = qp.submit_sweep(body)
    qp.batcher.tick(now=qp.batcher.clock.monotonic() + 1e6)
    return fut.result(timeout=60)


def _canon(resp: dict) -> str:
    return json.dumps(resp, sort_keys=True)


class _LoopbackTransport:
    """In-process stand-in for ApiTransport.get_bytes — serves the
    publisher's ring directly, with a kill switch for reconnect tests."""

    def __init__(self, pub: ReplicationPublisher) -> None:
        self.pub = pub
        self.down = False

    def get_bytes(self, path: str, timeout: float = 60) -> bytes:
        if self.down:
            raise OSError("leader unreachable")
        since = int(path.rsplit("since=", 1)[1])
        return self.pub.record_for(since)


@pytest.fixture
def plane_factory():
    planes = []

    def make(cache, **kw):
        kw.setdefault("start_thread", False)
        qp = QueryPlane(cache, **kw)
        planes.append(qp)
        return qp

    yield make
    for qp in planes:
        qp.close()


@pytest.fixture
def leader(plane_factory):
    """A leader cache with a published lease and an attached publisher."""
    cache = build_cache(
        queues=[Queue(name="default", weight=1)],
        pod_groups=[PodGroup(name="run0", namespace="c1", min_member=1,
                             queue="default")],
        nodes=[build_node(f"n{i}", cpu=8000, mem=16 * GiB, pods=32)
               for i in range(4)],
        pods=[build_pod("c1", "r0", "n0", PodPhase.RUNNING,
                        {"cpu": 6000, "memory": 4 * GiB},
                        group_name="run0")],
    )
    qp = plane_factory(cache)
    cache.replication = pub = ReplicationPublisher()
    try:
        _run(cache)
        pub.barrier()
        yield cache, qp, pub
    finally:
        pub.close()


def _make_follower(pub, plane_factory):
    fcache = FollowerCache()
    fqp = plane_factory(fcache)
    f = ReplicationFollower("http://unused", cache=fcache, query_plane=fqp,
                            transport=_LoopbackTransport(pub), poll_s=0.001)
    return f, fqp


def _churn(cache, i):
    """One ingest step: a new single-member gang that will bind."""
    cache.add_pod_group(PodGroup(name=f"churn-{i}", namespace="c1",
                                 min_member=1, queue="default"))
    cache.add_pod(build_pod("c1", f"churn-{i}-0", None, PodPhase.PENDING,
                            {"cpu": 200, "memory": 256 << 20},
                            group_name=f"churn-{i}"))


# ==========================================================================
# KBR1 wire format
# ==========================================================================


class TestWireFormat:
    def _record(self, kind=stream.FULL, **kw):
        base = dict(
            kind=kind, seq=3, version=17, prev_seq=2, prev_version=16,
            head_seq=3, head_version=17,
            full={}, delta={}, meta={"counts": [1, 2, 3, 4]},
            lease={"probe_rows": [0, 1]},
        )
        base.update(kw)
        return stream.ReplicationRecord(**base)

    def test_full_frame_round_trip(self):
        full = {
            "a": np.arange(12, dtype=np.float32).reshape(3, 4),
            "b": np.array([1, -2, 3], np.int64),
            "c": np.array([True, False]),
        }
        rec = self._record(full=full)
        out = stream.decode_record(stream.encode_record(rec))
        assert (out.kind, out.seq, out.version) == (stream.FULL, 3, 17)
        assert (out.head_seq, out.head_version) == (3, 17)
        assert out.meta == {"counts": [1, 2, 3, 4]}
        assert out.lease == {"probe_rows": [0, 1]}
        assert sorted(out.full) == ["a", "b", "c"]
        for k in full:
            assert out.full[k].dtype == full[k].dtype
            np.testing.assert_array_equal(out.full[k], full[k])
        # decoded arrays must be writable — the applier scatters in place
        out.full["a"][0, 0] = 99.0

    def test_delta_frame_round_trip(self):
        delta = {
            "x": (np.array([0, 5], np.int32),
                  np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)),
            "y": (np.array([2], np.int32), np.array([7], np.int64)),
        }
        rec = self._record(kind=stream.DELTA, delta=delta)
        out = stream.decode_record(stream.encode_record(rec))
        assert out.kind == stream.DELTA
        assert (out.prev_seq, out.prev_version) == (2, 16)
        assert sorted(out.delta) == ["x", "y"]
        for k, (rows, vals) in delta.items():
            np.testing.assert_array_equal(out.delta[k][0], rows)
            np.testing.assert_array_equal(out.delta[k][1], vals)

    def test_heartbeat_round_trip(self):
        rec = self._record(kind=stream.HEARTBEAT, prev_seq=-1,
                           prev_version=-1, meta={}, lease={})
        out = stream.decode_record(stream.encode_record(rec))
        assert out.kind == stream.HEARTBEAT
        assert not out.full and not out.delta

    def test_malformed_frames_rejected(self):
        rec = self._record(full={"a": np.zeros(4, np.float32)})
        frame = stream.encode_record(rec)
        with pytest.raises(ValueError):
            stream.decode_record(b"NOPE" + frame[4:])
        with pytest.raises(ValueError):
            stream.decode_record(frame[:6])          # truncated header len
        with pytest.raises(ValueError):
            stream.decode_record(frame[:-4])         # truncated payload

    def test_config_wire_round_trip(self):
        from kube_batch_tpu.ops.assignment import AllocateConfig
        from kube_batch_tpu.ops.eviction import EvictConfig

        for cfg in (AllocateConfig(), EvictConfig()):
            wire = stream.config_to_wire(cfg)
            json.dumps(wire)  # must be JSON-clean
            assert stream.config_from_wire(wire) == cfg
        with pytest.raises(TypeError):
            stream.config_to_wire(object())

    def test_meta_patch_round_trip(self):
        prev = {
            "task_keys": ["a/0", "a/1", "b/0"],
            "node_names": ["n0", "n1"],
            "job_uids": ["j0"],
            "queue_names": ["default"],
            "label_pair_bit": [["zone", "a", 0]],
            "taint_bit": [],
            "counts": [3, 2, 1, 1],
        }
        cur = {
            "task_keys": ["a/0", "c/0", "b/0", "c/1"],   # churn + growth
            "node_names": ["n0"],                        # shrink
            "job_uids": ["j0", "j1"],
            "queue_names": ["default"],
            "label_pair_bit": [["zone", "a", 0], ["zone", "b", 1]],
            "taint_bit": [["k", "v", "NoSchedule", 0]],
            "counts": [4, 1, 2, 1],
        }
        patch = stream.meta_patch(prev, cur)
        json.dumps(patch)
        assert stream.apply_meta_patch(prev, patch) == cur
        # unchanged lists travel as empty sets, unchanged maps are absent
        assert patch["queue_names"]["set"] == {}
        null = stream.meta_patch(cur, cur)
        assert "label_pair_bit" not in null and "taint_bit" not in null
        assert stream.apply_meta_patch(cur, null) == cur


# ==========================================================================
# leader/follower bit-match + delta chain
# ==========================================================================


BODY = {"queue": "default", "count": 2,
        "requests": {"cpu": 1500, "memory": 2 * GiB},
        "min_resources": {"cpu": 3000}}


class TestFollowerServing:
    def test_frozen_snapshot_bit_match(self, leader, plane_factory):
        cache, qp, pub = leader
        f, fqp = _make_follower(pub, plane_factory)
        assert f.run_once() == "applied"
        assert f.applier.applied_seq == 1
        r_leader = _probe(qp, BODY)
        r_follower = _probe(fqp, BODY)
        assert _canon(r_leader) == _canon(r_follower)
        assert r_follower["staleness"]["lag_cycles"] == 0
        # the sweep endpoint must agree bit-for-bit as well
        sweep_body = {"queue": "default", "max_count": 16,
                      "requests": {"cpu": 4000, "memory": 2 * GiB}}
        assert _canon(_sweep(qp, sweep_body)) == \
            _canon(_sweep(fqp, sweep_body))

    def test_delta_chain_under_churn_stays_bit_identical(
            self, leader, plane_factory):
        cache, qp, pub = leader
        f, fqp = _make_follower(pub, plane_factory)
        assert f.run_once() == "applied"
        lags = []
        for i in range(6):
            _churn(cache, i)
            _run(cache)
            pub.barrier()
            # pre-pull lag: how far the stream head ran ahead of this
            # follower — the staleness bound under per-cycle pulling
            rec = stream.decode_record(
                pub.record_for(f.applier.applied_seq))
            lags.append(rec.head_seq - f.applier.applied_seq)
            assert f.run_once() == "applied"
            assert _canon(_probe(qp, BODY)) == _canon(_probe(fqp, BODY))
        assert pub.counters()["records_delta"] >= 5, (
            "steady-state churn must travel as deltas, not full snapshots"
        )
        assert f.applier.applied_seq == 7
        assert float(np.percentile(lags, 99)) <= 1.0
        # caught up → the next pull is a heartbeat, not a re-send
        assert f.run_once() == "heartbeat"

    def test_meta_growth_crosses_the_wire(self, leader, plane_factory):
        """Churn that GROWS the row axes (new tasks/jobs) must decode on
        the follower — name lists patch, scatter rows stay in range."""
        cache, qp, pub = leader
        f, fqp = _make_follower(pub, plane_factory)
        f.run_once()
        for i in range(3):
            _churn(cache, 100 + i)
            _run(cache)
            pub.barrier()
            assert f.run_once() == "applied"
        body = {"queue": "default", "count": 1,
                "requests": {"cpu": 500, "memory": GiB}}
        assert _canon(_probe(qp, body)) == _canon(_probe(fqp, body))

    def test_follower_cache_rejects_ingest(self, leader, plane_factory):
        _, _, pub = leader
        f, _ = _make_follower(pub, plane_factory)
        with pytest.raises(ValueError, match="read-only replica"):
            f.cache.add_node(build_node("nx", cpu=1000, mem=GiB))
        with pytest.raises(ValueError, match="read-only replica"):
            f.cache.ingest_batch([])


# ==========================================================================
# gap → resync escalation, reconnect, warm restart
# ==========================================================================


class TestResyncAndRestart:
    def test_delta_gap_escalates_to_full_resync(self, leader, plane_factory):
        cache, qp, pub = leader
        f, fqp = _make_follower(pub, plane_factory)
        assert f.run_once() == "applied"
        for i in range(2):
            _churn(cache, i)
            _run(cache)
        pub.barrier()
        # feed the seq-3 delta to a follower at seq 1 — a chain gap; the
        # applier must refuse (not guess) and force the next pull full
        skipped = pub.record_for(2)
        assert stream.decode_record(skipped).kind == stream.DELTA
        assert f.applier.apply(skipped) == "resync"
        assert f.applier.gaps == 1
        assert f.applier.applied_seq == 1, "a refused record must not apply"
        f._force_full = True
        assert f.run_once() == "applied"
        assert f.applier.applied_seq == 3
        assert f.applier.full_adoptions >= 1
        assert _canon(_probe(qp, BODY)) == _canon(_probe(fqp, BODY))

    def test_ring_falloff_serves_synthesized_full(self, plane_factory):
        cache = build_cache(
            queues=[Queue(name="default", weight=1)],
            nodes=[build_node("n0", cpu=8000, mem=16 * GiB)],
        )
        qp = plane_factory(cache)
        cache.replication = pub = ReplicationPublisher(ring_size=1)
        try:
            _run(cache)
            for i in range(3):
                _churn(cache, i)
                _run(cache)
            pub.barrier()
            # a follower at seq 1 asks for seq 2 — long gone from a
            # 1-deep ring; the leader must synthesize a full from mirrors
            rec = stream.decode_record(pub.record_for(1))
            assert rec.kind == stream.FULL
            assert rec.seq == pub.counters()["head_seq"]
            f, fqp = _make_follower(pub, plane_factory)
            assert f.run_once() == "applied"
            assert _canon(_probe(qp, BODY)) == _canon(_probe(fqp, BODY))
        finally:
            pub.close()

    def test_reconnect_after_leader_outage(self, leader, plane_factory):
        cache, qp, pub = leader
        f, fqp = _make_follower(pub, plane_factory)
        assert f.run_once() == "applied"
        f.transport.down = True
        assert f.run_once() == "error"
        assert f.pull_errors == 1
        # leader kept cycling during the outage
        for i in range(2):
            _churn(cache, i)
            _run(cache)
        pub.barrier()
        f.transport.down = False
        # pull 1: the seq-2 delta is still in the ring → chain intact
        assert f.run_once() == "applied"
        assert f.run_once() == "applied"
        assert f.applier.applied_seq == 3
        assert _canon(_probe(qp, BODY)) == _canon(_probe(fqp, BODY))

    def test_restart_readopts_warm(self, leader, plane_factory):
        cache, qp, pub = leader
        f, fqp = _make_follower(pub, plane_factory)
        assert f.run_once() == "applied"
        app = f.applier
        # a synced applier re-adopts WARM: buffers + resident survive
        mode = app.revalidate_resident()
        assert mode["mode"] == "warm" and mode["resident_version"] > 0
        static_field = next(iter(app._static_dev))
        buf_before = app._static_dev[static_field][1]
        resident_before = app.resident
        # a forced full re-adoption of UNCHANGED state must keep every
        # stamp — same device buffers, no re-upload
        f._force_full = True
        assert f.run_once() == "applied"
        assert app._static_dev[static_field][1] is buf_before
        assert app.resident is resident_before
        assert _canon(_probe(qp, BODY)) == _canon(_probe(fqp, BODY))
        # a fresh applier (no synced state) starts cold
        f2, _ = _make_follower(pub, plane_factory)
        assert f2.applier.revalidate_resident()["mode"] == "cold"


# ==========================================================================
# /v1/whatif/sweep — server-side "how many replicas fit"
# ==========================================================================


class TestSweep:
    def test_sweep_matches_brute_force(self, leader, plane_factory):
        cache, qp, _ = leader
        body = {"queue": "default", "max_count": 16,
                "requests": {"cpu": 4000, "memory": 2 * GiB}}
        resp = _sweep(qp, body)
        # brute force: probe every count as its own all-or-nothing gang
        brute = 0
        for c in range(1, 17):
            r = _probe(qp, {"queue": "default", "count": c,
                            "requests": {"cpu": 4000, "memory": 2 * GiB}})
            if r["feasible"]:
                brute = c
        assert resp["max_fit"] == brute == 6
        assert resp["feasible"]
        assert resp["probes"] < 16, "binary search must beat linear scan"
        assert resp["staleness"]["lag_cycles"] == 0

    def test_sweep_infeasible_and_validation(self, leader, plane_factory):
        cache, qp, _ = leader
        none_fit = _sweep(qp, {"queue": "default", "max_count": 8,
                               "requests": {"cpu": 64000}})
        assert none_fit["max_fit"] == 0 and not none_fit["feasible"]
        with pytest.raises(WhatifError):
            qp.submit_sweep({"queue": "default", "max_count": 0,
                             "requests": {"cpu": 100}})
        with pytest.raises(WhatifError):
            qp.submit_sweep({"queue": "default", "max_count": 65,
                             "requests": {"cpu": 100}})
        with pytest.raises(WhatifError):
            qp.submit_sweep({"queue": "default", "max_count": 4,
                             "requests": {"cpu": 100}, "evictions": True})
