"""Tier B jaxpr audit: the production registry must trace clean, and each
check must catch its planted bug — a deliberate f64 upcast, an in-graph
transfer, a host callback, and a donation mismatch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kube_batch_tpu.analysis.jaxpr_audit import (
    AUDIT_RULES,
    REGISTRY,
    EntryPoint,
    audit_entry,
    run_audit,
)


def _entry(name, build, **kw):
    return EntryPoint(name=name, build=build, **kw)


def _vec():
    from jax import ShapeDtypeStruct as S

    return S((8,), jnp.float32)


class TestRegistryClean:
    def test_production_registry_has_zero_findings(self):
        findings = run_audit()
        assert findings == [], "\n" + "\n".join(f.render() for f in findings)

    def test_registry_covers_the_hot_path(self):
        names = {e.name for e in REGISTRY}
        assert any("allocate_solve" in n for n in names)
        assert any("allocate_topk_solve" in n for n in names)
        assert any("evict_solve" in n for n in names)
        assert any("resident" in n for n in names)
        assert any("pallas" in n for n in names)
        assert any("masked_topk_blocks" in n for n in names)
        assert any("enqueue_gate" in n for n in names)
        assert any("topk-inert" in n for n in names)

    def test_sharded_variants_traced_on_the_virtual_mesh(self):
        """The conftest's forced 8-device CPU mesh stands in for multi-chip
        hardware: the sharded solve variants and both mesh scatters must be
        registered and trace clean (KBT101-104 over the sharded path)."""
        from kube_batch_tpu.analysis.jaxpr_audit import sharded_registry

        assert len(jax.devices()) >= 2
        sharded = sharded_registry()
        names = {e.name for e in sharded}
        assert any("sharded_allocate_solve" in n for n in names)
        assert any("sharded_allocate_topk_solve" in n for n in names)
        assert any("sharded_failure_histogram" in n for n in names)
        assert any("sharded_evict_solve" in n for n in names)
        assert any("scatter_sharded" in n for n in names)
        assert any("scatter_repl" in n for n in names)
        findings = run_audit(registry=sharded)
        assert findings == [], "\n" + "\n".join(f.render() for f in findings)


class TestPlantedBugs:
    def test_planted_f64_upcast_is_detected(self):
        # np.float64 scalar promotes the whole expression under x64 — the
        # exact hazard class the pallas round head shipped (fixed this PR)
        def build():
            fn = jax.jit(lambda x: x * np.float64(2.0))
            return fn, (_vec(),)

        findings = audit_entry(_entry("planted.f64", build))
        assert [f.rule for f in findings] == ["KBT101"]
        assert "float64" in findings[0].message

    def test_planted_astype_f64_is_detected(self):
        def build():
            fn = jax.jit(lambda x: x.astype(jnp.float64).sum())
            return fn, (_vec(),)

        findings = audit_entry(_entry("planted.astype", build))
        assert [f.rule for f in findings] == ["KBT101"]

    def test_planted_concrete_device_put_is_detected(self):
        dev = jax.devices()[0]

        def build():
            fn = jax.jit(lambda x: jax.device_put(x, dev) + 1.0)
            return fn, (_vec(),)

        findings = audit_entry(_entry("planted.transfer", build))
        assert [f.rule for f in findings] == ["KBT102"]

    def test_planted_host_callback_is_detected(self):
        def build():
            def f(x):
                y = jax.pure_callback(
                    lambda v: np.asarray(v),
                    jax.ShapeDtypeStruct((8,), jnp.float32), x,
                )
                return y + 1.0

            return jax.jit(f), (_vec(),)

        findings = audit_entry(_entry("planted.callback", build))
        assert [f.rule for f in findings] == ["KBT103"]

    def test_planted_donation_mismatch_is_detected(self):
        # registry says "donates arg 0 on every backend"; the wrapper
        # doesn't — the silent-regression shape KBT104 exists for
        def build():
            fn = jax.jit(lambda d, r: d.at[r].set(0.0))
            return fn, (_vec(), jax.ShapeDtypeStruct((2,), jnp.int32))

        findings = audit_entry(
            _entry("planted.donation", build, donate={"*": (0,)}))
        assert [f.rule for f in findings] == ["KBT104"]

    def test_declared_donation_passes(self):
        def build():
            fn = jax.jit(lambda d, r: d.at[r].set(0.0), donate_argnums=(0,))
            return fn, (_vec(), jax.ShapeDtypeStruct((2,), jnp.int32))

        findings = audit_entry(
            _entry("planted.donation_ok", build, donate={"*": (0,)}))
        assert findings == []

    def test_broken_entry_reports_instead_of_reading_clean(self):
        def build():
            raise RuntimeError("registry rot")

        findings = audit_entry(_entry("planted.broken", build))
        assert [f.rule for f in findings] == ["KBT000"]
        assert "failed to trace" in findings[0].message


class TestSuppression:
    def _f64_entry(self, allow):
        def build():
            fn = jax.jit(lambda x: x * np.float64(2.0))
            return fn, (_vec(),)

        return _entry("planted.sup", build, allow=allow)

    def test_allow_with_reason_suppresses(self):
        findings = audit_entry(
            self._f64_entry({"KBT101": "fixture: deliberate upcast"}))
        assert findings == []

    def test_allow_without_reason_is_itself_a_finding(self):
        findings = audit_entry(self._f64_entry({"KBT101": "  "}))
        assert [f.rule for f in findings] == ["KBT000"]

    def test_select_filters_audit_rules(self):
        entry = self._f64_entry({})
        findings = run_audit(registry=[entry], select=["KBT102"])
        assert findings == []
        findings = run_audit(registry=[entry], select=["KBT101"])
        assert [f.rule for f in findings] == ["KBT101"]


class TestCatalog:
    def test_audit_rules_documented(self):
        assert set(AUDIT_RULES) == {"KBT101", "KBT102", "KBT103", "KBT104"}
        for title in AUDIT_RULES.values():
            assert title


@pytest.mark.slow
class TestTiming:
    def test_full_audit_is_subsecond_after_warm_import(self):
        import time

        t0 = time.perf_counter()
        run_audit()
        assert time.perf_counter() - t0 < 10.0
