"""Event-driven pipelined cycles: bit-exactness vs the serial oracle over
randomized churn, ingest staging semantics, trigger semantics, the in-flight
bind guard, and the budget-shed interaction with the overlapped close.

The pipelined loop's contract: same binds, same statuses, same queue
writebacks as the serial wait.Until loop — the overlap only moves WHEN the
egress happens, never WHAT it says.  These tests run the two modes over
identical seed-deterministic churn streams and diff the observable end
state.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import metrics as prom_metrics
from kube_batch_tpu.metrics.metrics import STATUS_WRITES_SHED
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.pod import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    Queue,
)
from kube_batch_tpu.api.types import PodPhase
from kube_batch_tpu.cache.cache import SchedulerCache, StatusFlush
from kube_batch_tpu.cache.fake import FakeBinder, FakeEvictor, FakeStatusUpdater
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu.scheduler import CycleTrigger, Scheduler
from kube_batch_tpu.sim import kubelet as kl
from kube_batch_tpu.testing.synthetic import GiB


def _mk_cache(n_nodes=6, n_queues=2):
    cache = SchedulerCache(
        binder=FakeBinder(), evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
    )
    for q in range(n_queues):
        cache.add_queue(Queue(name=f"q{q}", uid=f"uq{q}", weight=q + 1))
    for i in range(n_nodes):
        cache.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": 16000.0, "memory": 64 * GiB, "pods": 110.0},
        ))
    return cache


def _mk_scheduler(cache) -> Scheduler:
    return Scheduler(cache, conf=load_scheduler_conf(None))


class _Churner:
    """Seed-deterministic churn through the real ingest surface — applied
    IDENTICALLY to the serial and pipelined caches each cycle."""

    def __init__(self, cache, seed, n_queues=2):
        self.cache = cache
        self.rng = np.random.default_rng(seed)
        self.n_queues = n_queues
        self.serial = 0
        self.gangs = []

    def add_gang(self):
        self.serial += 1
        g = f"g{self.serial}"
        size = int(self.rng.integers(1, 4))
        self.cache.add_pod_group(PodGroup(
            name=g, namespace="churn", uid=f"pg-{g}", min_member=size,
            queue=f"q{int(self.rng.integers(self.n_queues))}",
            creation_index=self.serial,
        ))
        for k in range(size):
            self.cache.add_pod(Pod(
                name=f"{g}-{k}", namespace="churn", uid=f"pod-{g}-{k}",
                requests={"cpu": float(self.rng.choice([250.0, 500.0, 1000.0])),
                          "memory": 1 * GiB},
                annotations={GROUP_NAME_ANNOTATION: g},
                phase=PodPhase.PENDING,
                creation_index=self.serial * 100 + k,
            ))
        self.gangs.append(g)

    def complete_gang(self):
        if not self.gangs:
            return
        g = self.gangs.pop(int(self.rng.integers(len(self.gangs))))
        job_uid = f"churn/{g}"
        job = self.cache.jobs.get(job_uid)
        keys = sorted(job.tasks.keys()) if job is not None else []
        for key in keys:
            kl.delete_pod(self.cache, key)
        self.cache.delete_pod_group(job_uid)

    def flip_statuses(self):
        pods = [p for p in self.cache.pods.values() if p.node_name]
        if not pods:
            return
        pods.sort(key=lambda p: p.key())
        for p in pods[: int(self.rng.integers(1, 3))]:
            if p.phase == PodPhase.PENDING:
                kl.set_running(self.cache, p.key(), p.node_name)
            elif p.phase == PodPhase.RUNNING and self.rng.random() < 0.5:
                kl.set_succeeded(self.cache, p.key())

    def step(self):
        r = self.rng.random()
        if r < 0.45:
            self.add_gang()
        elif r < 0.70:
            self.complete_gang()
        else:
            self.flip_statuses()


def _observable_state(cache) -> dict:
    """Everything the pipelined loop promises not to change: durable
    bindings, pod phases, podgroup statuses, conditions, queue writebacks."""
    pg_status = {}
    for uid, job in sorted(cache.jobs.items()):
        pg = job.pod_group
        if pg is not None:
            pg_status[uid] = (pg.phase, pg.running, pg.failed, pg.succeeded)
    return {
        "binds": dict(cache.binder.binds),
        "pods": {k: (p.node_name, p.phase)
                 for k, p in sorted(cache.pods.items())},
        "pg_status": pg_status,
        "conditions": dict(cache.pod_conditions),
        "queue_statuses": dict(cache.status_updater.queue_statuses),
    }


class TestPipelinedBitExact:
    @pytest.mark.parametrize("seed", [0, 11, 42])
    def test_pipelined_matches_serial_over_randomized_churn(self, seed):
        """Same churn stream, serial vs pipelined cycles: identical binds
        (no duplicates, no losses), identical pod/podgroup statuses,
        identical conditions and queue writebacks."""
        c_serial, c_pipe = _mk_cache(), _mk_cache()
        s_serial, s_pipe = _mk_scheduler(c_serial), _mk_scheduler(c_pipe)
        ch_serial = _Churner(c_serial, seed)
        ch_pipe = _Churner(c_pipe, seed)
        for _ in range(3):
            ch_serial.add_gang()
            ch_pipe.add_gang()
        for cycle in range(10):
            ch_serial.step()
            ch_pipe.step()
            s_serial.run_once()
            s_pipe.run_once_pipelined()
            s_pipe.drain_pipeline()
        want = _observable_state(c_serial)
        got = _observable_state(c_pipe)
        for field in want:
            assert got[field] == want[field], (
                f"seed={seed}: {field} diverged between serial and "
                f"pipelined cycles"
            )
        # no duplicate binds: every bound pod was dispatched exactly once
        keys = [k for k in c_pipe.binder.channel]
        assert len(keys) == len(set(keys)), "duplicate bind dispatch"

    def test_pipelined_with_staged_ingest_matches_serial(self):
        """The staged-ingest path (churn lands in the staging buffer, the
        cycle drains it under one lock) reaches the same end state as
        direct ingest + serial cycles."""
        c_serial, c_pipe = _mk_cache(), _mk_cache()
        s_serial, s_pipe = _mk_scheduler(c_serial), _mk_scheduler(c_pipe)
        c_pipe.enable_ingest_staging()
        ch_serial = _Churner(c_serial, 5)
        ch_pipe = _Churner(c_pipe, 5)
        for cycle in range(8):
            ch_serial.step()
            ch_pipe.step()  # staged, applied at the next cycle's drain
            s_serial.run_once()
            s_pipe.run_once_pipelined()
            s_pipe.drain_pipeline()
        # flush any residue and settle both sides one more cycle
        c_pipe.disable_ingest_staging()
        s_serial.run_once()
        s_pipe.run_once_pipelined()
        s_pipe.drain_pipeline()
        assert _observable_state(c_pipe) == _observable_state(c_serial)


class TestStagedIngest:
    def test_staged_events_invisible_until_drain(self):
        cache = _mk_cache(n_nodes=1)
        cache.enable_ingest_staging()
        pod = Pod(name="p0", namespace="ns", uid="u0",
                  requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                  creation_index=1)
        cache.add_pod(pod)
        assert "ns/p0" not in cache.pods
        assert cache.drain_staged_ingest() == 1
        assert "ns/p0" in cache.pods

    def test_staged_arrival_fires_wake_signal(self):
        cache = _mk_cache(n_nodes=1)
        wakes = []
        cache.set_ingest_signal(lambda: wakes.append(1))
        cache.enable_ingest_staging()
        cache.add_pod(Pod(name="p1", namespace="ns", uid="u1",
                          requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                          creation_index=1))
        assert wakes, "staged arrival must wake the cycle trigger"

    def test_direct_dirty_advance_fires_wake_signal(self):
        cache = _mk_cache(n_nodes=1)
        wakes = []
        cache.set_ingest_signal(lambda: wakes.append(1))
        cache.add_pod(Pod(name="p2", namespace="ns", uid="u2",
                          requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                          creation_index=1))
        assert wakes, "an un-staged ingest's dirty advance must wake too"

    def test_disable_drains_residue(self):
        cache = _mk_cache(n_nodes=1)
        cache.enable_ingest_staging()
        cache.add_pod(Pod(name="p3", namespace="ns", uid="u3",
                          requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                          creation_index=1))
        cache.disable_ingest_staging()
        assert "ns/p3" in cache.pods

    def test_drain_does_not_retrigger_its_own_cycle(self):
        """The cycle's drain applies churn the session about to open will
        consume — its dirty advances must not re-wake the trigger (which
        would schedule a guaranteed no-op follow-up cycle every burst)."""
        cache = _mk_cache(n_nodes=1)
        wakes = []
        cache.set_ingest_signal(lambda: wakes.append(1))
        cache.enable_ingest_staging()
        cache.add_pod(Pod(name="d0", namespace="ns", uid="ud0",
                          requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                          creation_index=1))
        staged_wakes = len(wakes)
        assert staged_wakes >= 1
        assert cache.drain_staged_ingest() == 1
        assert len(wakes) == staged_wakes, (
            "the drain's own applies re-woke the trigger"
        )

    def test_direct_batch_apply_still_wakes(self):
        """ingest_batch with staging OFF is real external churn — its one
        coalesced dirty advance must wake the loop (unlike the drain)."""
        cache = _mk_cache(n_nodes=1)
        wakes = []
        cache.set_ingest_signal(lambda: wakes.append(1))
        pod = Pod(name="d1", namespace="ns", uid="ud1",
                  requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                  creation_index=1)
        cache.ingest_batch([(cache.add_pod, pod)])
        assert wakes

    def test_staged_arrival_stamps_clock_at_stage_time(self):
        """The arrival→decision clock starts when the pod lands in the
        staging buffer, not when the next cycle's drain applies it."""
        cache = _mk_cache(n_nodes=1)
        cache.enable_ingest_staging()
        cache.add_pod(Pod(name="s0", namespace="ns", uid="us0",
                          requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                          creation_index=1))
        assert "ns/s0" in cache._arrival_ts, "stamp must precede the drain"
        t0 = cache._arrival_ts["ns/s0"]
        cache.drain_staged_ingest()
        assert cache._arrival_ts["ns/s0"] == t0, (
            "the drain's apply must keep the stage-time stamp"
        )

    def test_ingest_batch_reports_partial_failure(self):
        cache = _mk_cache(n_nodes=1)
        good = Pod(name="pf0", namespace="ns", uid="upf0",
                   requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                   creation_index=1)

        def boom(obj):
            raise ValueError("bad element")

        applied = cache.ingest_batch(
            [(cache.add_pod, good), (boom, object())])
        assert applied == 1, "only successful applies count"
        assert "ns/pf0" in cache.pods

    def test_ingest_batch_single_version_advance(self):
        cache = _mk_cache(n_nodes=1)
        v0 = cache.dirty.version
        pods = [
            Pod(name=f"b{i}", namespace="ns", uid=f"ub{i}",
                requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                creation_index=10 + i)
            for i in range(5)
        ]
        applied = cache.ingest_batch([(cache.add_pod, p) for p in pods])
        assert applied == 5
        assert all(f"ns/b{i}" in cache.pods for i in range(5))
        assert cache.dirty.version == v0 + 1, (
            "a batch advances the dirty version ONCE"
        )
        # per-kind dirty sets still carry every element for the delta open
        assert len(cache.dirty.pods) >= 5


class TestCycleTrigger:
    def test_notify_wakes_as_ingest(self):
        trig = CycleTrigger()
        trig.notify()
        t0 = time.monotonic()
        reason = trig.wait_for_work(time.monotonic(), 0.0, 5.0)
        assert reason == "ingest"
        assert time.monotonic() - t0 < 1.0

    def test_idle_wakes_at_the_floor(self):
        trig = CycleTrigger()
        start = time.monotonic()
        reason = trig.wait_for_work(start, 0.0, 0.08)
        assert reason == "floor"
        assert time.monotonic() - start >= 0.07

    def test_min_period_coalesces_bursts(self):
        """A signal raised immediately after the cycle start must still
        wait out the rate floor — bursts become one cycle per min_period."""
        trig = CycleTrigger()
        start = time.monotonic()
        trig.notify()
        reason = trig.wait_for_work(start, 0.08, 5.0)
        assert reason == "ingest"
        assert time.monotonic() - start >= 0.07

    def test_poll_consumes_pending(self):
        trig = CycleTrigger()
        trig.notify()
        assert trig.poll() is True
        assert trig.poll() is False

    def test_cross_thread_notify(self):
        trig = CycleTrigger()
        threading.Timer(0.03, trig.notify).start()
        reason = trig.wait_for_work(time.monotonic(), 0.0, 5.0)
        assert reason == "ingest"


class TestAdaptiveMinPeriod:
    """KB_PERIOD_MIN unset → the trigger's coalescing floor tracks an EWMA
    of the cycle's own measured cost (a 200 ms solve shouldn't re-trigger
    every 50 ms; a 10 ms cycle shouldn't wait out 50); setting the env
    pins the static floor back."""

    def _sched(self, **env):
        import os

        saved = {k: os.environ.get(k) for k in ("KB_PERIOD_MIN",)}
        os.environ.pop("KB_PERIOD_MIN", None)
        os.environ.update(env)
        try:
            return Scheduler(_mk_cache(), conf=load_scheduler_conf(None),
                             schedule_period=1.0)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def test_adapts_to_measured_cost(self):
        sched = self._sched()
        assert not sched.min_period_pinned
        assert sched.min_period == pytest.approx(0.05)  # static default
        sched._note_cycle_cost(0.2)
        assert sched.cycle_cost_ewma == pytest.approx(0.2)
        assert sched.min_period == pytest.approx(0.2)
        # EWMA smoothing: a single fast outlier moves the floor by alpha
        sched._note_cycle_cost(0.0)
        expect = (1.0 - Scheduler.EWMA_ALPHA) * 0.2
        assert sched.cycle_cost_ewma == pytest.approx(expect)
        assert sched.min_period == pytest.approx(expect)

    def test_floor_and_ceiling_clamps(self):
        sched = self._sched()
        # degenerate fast cycles clamp at the busy-spin floor, not zero
        for _ in range(50):
            sched._note_cycle_cost(0.0)
        assert sched.min_period == pytest.approx(Scheduler.MIN_PERIOD_FLOOR)
        # a pathological cycle cost clamps at max_period (idle tick stays
        # reachable)
        for _ in range(50):
            sched._note_cycle_cost(100.0)
        assert sched.min_period == pytest.approx(sched.max_period)
        # negative (clock skew) samples are ignored
        ewma = sched.cycle_cost_ewma
        sched._note_cycle_cost(-1.0)
        assert sched.cycle_cost_ewma == ewma

    def test_env_pin_restores_static_floor(self):
        sched = self._sched(KB_PERIOD_MIN="0.123")
        assert sched.min_period_pinned
        assert sched.min_period == pytest.approx(0.123)
        sched._note_cycle_cost(0.5)
        # the EWMA still tracks (observability), the floor does not move
        assert sched.cycle_cost_ewma == pytest.approx(0.5)
        assert sched.min_period == pytest.approx(0.123)

    def test_pipelined_loop_feeds_the_ewma(self):
        """The real loop wires measured cycle costs into the floor: after a
        brief pipelined run of an idle cache, the EWMA is populated and the
        unpinned floor has left the static 50 ms default (fast idle cycles
        pull it down toward the busy-spin floor)."""
        sched = self._sched()
        sched.pipelined = True
        sched.max_period = 0.01  # tick fast so several cycles run
        t = threading.Thread(target=sched.run_forever, daemon=True)
        t.start()
        try:
            time.sleep(0.5)
        finally:
            sched.stop()
            t.join(timeout=5.0)
        assert sched.cycle_cost_ewma is not None
        assert sched.min_period < 0.05


class TestRunForeverPipelined:
    def test_burst_binds_and_shutdown_drains(self):
        """run_forever in pipelined mode: a pod staged mid-loop is bound
        without waiting out the idle period, and stop() drains every
        in-flight stage (staging buffer empty, writeback joined)."""
        cache = _mk_cache()
        sched = Scheduler(cache, conf=load_scheduler_conf(None),
                          schedule_period=5.0)
        sched.pipelined = True
        sched.min_period = 0.0
        sched.max_period = 5.0  # idle floor far beyond the test timeout
        t = threading.Thread(target=sched.run_forever, daemon=True)
        t.start()
        try:
            time.sleep(0.2)  # loop reaches its idle wait
            cache.add_pod_group(PodGroup(
                name="burst", namespace="ns", uid="pg-burst", min_member=1,
                queue="q0", creation_index=1,
            ))
            cache.add_pod(Pod(
                name="burst-0", namespace="ns", uid="u-burst",
                requests={"cpu": 500.0}, phase=PodPhase.PENDING,
                annotations={GROUP_NAME_ANNOTATION: "burst"},
                creation_index=2,
            ))
            # the arrival must schedule a cycle well before the 5 s floor
            assert cache.binder.event.wait(3.0), (
                "burst arrival did not trigger a cycle before the idle "
                "period"
            )
        finally:
            sched.stop()
            t.join(timeout=10.0)
        assert not t.is_alive()
        with cache._ingest_lock:  # guarded-access corroborator: hold the domain lock
            assert cache._ingest_staged == [], "shutdown must drain staging"
        assert sched._wb_future is None, "shutdown must join the writeback"
        assert cache.binder.binds.get("ns/burst-0") is not None

    def test_serial_oracle_knob(self, monkeypatch):
        monkeypatch.setenv("KB_PIPELINE", "0")
        sched = Scheduler(_mk_cache(n_nodes=1),
                          conf=load_scheduler_conf(None))
        assert sched.pipelined is False


class TestBudgetShedOverlappedClose:
    def test_stage_captures_degraded_verdict(self):
        """The degraded verdict is taken at STAGE time (while the budget
        shed flag is visible on the cycle thread), not at writeback time —
        the overlapped flush sheds even though the flag has been reset by
        the time the worker runs."""
        cache = _mk_cache()
        ch = _Churner(cache, 3)
        ch.add_gang()
        sched = _mk_scheduler(cache)
        sched.run_once()  # settle: podgroups now have derived statuses
        ch.add_gang()
        ssn = open_session(cache, sched.conf.tiers)
        ssn.action_names = [a.name for a in sched.actions]
        for action in sched.actions:
            action.execute(ssn)
        cache.shed_status_writes = True
        try:
            flush = close_session(ssn, stage_flush=True)
        finally:
            cache.shed_status_writes = False
        assert flush is not None and flush.degraded, (
            "stage_status_flush must capture the shed verdict at stage time"
        )
        wrote_before = len(cache.status_updater.pod_groups)
        shed_before = STATUS_WRITES_SHED._values.get((), 0)
        cache.run_status_flush(flush)
        cache.flush_binds()
        assert len(cache.status_updater.pod_groups) == wrote_before, (
            "a degraded flush must shed the podgroup writes"
        )
        if flush.to_write:
            assert STATUS_WRITES_SHED._values.get((), 0) > \
                shed_before

    def test_statusflush_is_value_snapshotted(self):
        """The handoff carries CLONES: mutating the live PodGroup after
        staging must not change what the writeback writes."""
        cache = _mk_cache()
        ch = _Churner(cache, 9)
        ch.add_gang()
        sched = _mk_scheduler(cache)
        ssn = open_session(cache, sched.conf.tiers)
        ssn.action_names = [a.name for a in sched.actions]
        for action in sched.actions:
            action.execute(ssn)
        flush = close_session(ssn, stage_flush=True)
        assert isinstance(flush, StatusFlush)
        live = {id(j.pod_group) for j in cache.jobs.values()
                if j.pod_group is not None}
        for pg in flush.to_write:
            assert id(pg) not in live, (
                "staged podgroup writes must be clones, not live objects"
            )
        cache.run_status_flush(flush)
        cache.flush_binds()


class TestWritebackRobustness:
    def test_failed_cycle_still_flushes_staged_writeback(self):
        """A cycle that dies in an action has ALREADY staged its flush (and
        recorded its queue deltas as written) — the handoff must still
        reach the writeback stage, or those deltas are suppressed until the
        counts next change."""
        cache = _mk_cache()
        ch = _Churner(cache, 7)
        ch.add_gang()
        sched = _mk_scheduler(cache)
        sched.run_once_pipelined()
        sched.drain_pipeline()

        class Boom:
            name = "boom"

            def execute(self, ssn):
                raise RuntimeError("injected action failure")

        ch.add_gang()  # fresh queue counts for the failing cycle to derive
        sched.actions = sched.actions + [Boom()]
        try:
            with pytest.raises(RuntimeError):
                sched.run_once_pipelined()
        finally:
            sched.actions = sched.actions[:-1]
        sched.drain_pipeline()
        # the invariant: every queue delta recorded as written at stage
        # time was actually written by the overlapped flush
        assert cache.status_updater.queue_statuses == \
            cache._queue_status_written

    def test_one_failing_podgroup_write_does_not_abort_queue_writes(self):
        """A single updater exception in the pod-group write loop must not
        skip the remaining writes or the queue deltas the stage already
        recorded as written."""
        cache = _mk_cache()
        fails = {"n": 1}
        real = cache.status_updater.update_pod_group

        def flaky(pg):
            if fails["n"]:
                fails["n"] -= 1
                raise OSError("transient apiserver error")
            real(pg)

        cache.status_updater.update_pod_group = flaky
        ch = _Churner(cache, 13)
        ch.add_gang()
        ch.add_gang()
        sched = _mk_scheduler(cache)
        sched.run_once_pipelined()
        sched.drain_pipeline()
        assert fails["n"] == 0, "the flaky write fired"
        assert cache.status_updater.queue_statuses == \
            cache._queue_status_written


class TestCloseEdgeCases:
    def test_empty_session_close_stages_queue_writes(self):
        """A pipelined cycle with no jobs (the idle tick) takes the
        non-columnar close branch — its queue zero-outs must still cross
        the staged handoff, not write inline while the previous cycle's
        writeback worker may be running."""
        cache = _mk_cache()
        ch = _Churner(cache, 21)
        ch.add_gang()
        sched = _mk_scheduler(cache)
        sched.run_once_pipelined()
        sched.drain_pipeline()
        ch.complete_gang()  # empty cluster: next close zero-outs the queue
        ssn = open_session(cache, sched.conf.tiers)
        ssn.action_names = [a.name for a in sched.actions]
        for action in sched.actions:
            action.execute(ssn)
        writes_before = dict(cache.status_updater.queue_statuses)
        flush = close_session(ssn, stage_flush=True)
        assert flush is not None, "empty close must stage, not write inline"
        assert cache.status_updater.queue_statuses == writes_before, (
            "the close wrote inline instead of staging"
        )
        cache.run_status_flush(flush)
        cache.flush_binds()
        assert cache.status_updater.queue_statuses == \
            cache._queue_status_written

    def test_close_failure_after_staging_still_flushes(self):
        """end_exclusive_session raising AFTER the stage must not drop the
        flush — the scheduler recovers it from the session stash."""
        cache = _mk_cache()
        ch = _Churner(cache, 29)
        ch.add_gang()
        sched = _mk_scheduler(cache)
        sched.run_once_pipelined()
        sched.drain_pipeline()
        ch.add_gang()
        real_end = cache.end_exclusive_session
        fired = {"n": 0}

        def flaky_end():
            real_end()  # cache stays sane; the failure is after the work
            if fired["n"] == 0:
                fired["n"] = 1
                raise RuntimeError("injected close failure")

        cache.end_exclusive_session = flaky_end
        try:
            with pytest.raises(RuntimeError):
                sched.run_once_pipelined()
        finally:
            cache.end_exclusive_session = real_end
        sched.drain_pipeline()
        assert fired["n"] == 1
        assert cache.status_updater.queue_statuses == \
            cache._queue_status_written


class TestInflightBindGuard:
    def test_update_pod_keeps_unacked_dispatch(self):
        """A client update landing between the bind dispatch and its ack
        must keep the dispatched placement — the pipelined loop widens that
        window to a whole stage."""
        cache = _mk_cache(n_nodes=1)
        pod = Pod(name="w0", namespace="ns", uid="uw0",
                  requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                  creation_index=1)
        cache.add_pod(pod)
        cache._inflight_bind_hosts["ns/w0"] = "n0"
        update = Pod(name="w0", namespace="ns", uid="uw0",
                     requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                     creation_index=1)
        cache.update_pod(update)
        assert cache.pods["ns/w0"].node_name == "n0", (
            "unacked async bind clobbered by a stale client update"
        )

    def test_failed_dispatch_rolls_back_optimistic_stamp(self):
        cache = _mk_cache(n_nodes=1)
        pod = Pod(name="w1", namespace="ns", uid="uw1",
                  requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                  creation_index=1)
        cache.add_pod(pod)
        cache._inflight_bind_hosts["ns/w1"] = "n0"
        update = Pod(name="w1", namespace="ns", uid="uw1",
                     requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                     creation_index=1)
        cache.update_pod(update)  # copies the in-flight placement
        stored = cache.pods["ns/w1"]
        assert stored.node_name == "n0"
        # the dispatch FAILS: the optimistic stamp on the replacement pod
        # object must roll back (the apiserver never bound it)
        cache._settle_inflight([("ns/w1", pod, "n0")], bound=False)
        assert cache.pods["ns/w1"].node_name is None
        assert "ns/w1" not in cache._inflight_bind_hosts
        # the failed pod's latency clock re-arms (the repair re-decision
        # must produce a sample) ...
        assert "ns/w1" in cache._arrival_ts

    def test_failed_settle_for_deleted_pod_leaks_no_clock(self):
        # ... but a pod DELETED while its dispatch was in flight must not
        # plant a never-popped arrival entry
        cache = _mk_cache(n_nodes=1)
        pod = Pod(name="w2", namespace="ns", uid="uw2",
                  requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                  creation_index=1)
        cache.add_pod(pod)
        cache._inflight_bind_hosts["ns/w2"] = "n0"
        cache.delete_pod(pod)
        cache._settle_inflight([("ns/w2", pod, "n0")], bound=False)
        assert "ns/w2" not in cache._arrival_ts


class TestDecisionLatency:
    def test_bind_decision_observes_latency(self):
        sink = []
        prom_metrics.set_decision_latency_sink(sink)
        try:
            cache = _mk_cache()
            ch = _Churner(cache, 1)
            ch.add_gang()
            sched = _mk_scheduler(cache)
            sched.run_once()
        finally:
            prom_metrics.set_decision_latency_sink(None)
        assert sink, "bind decisions must observe arrival→decision latency"
        assert all(ms >= 0.0 for ms in sink)

    def test_latency_clock_survives_status_replays(self):
        """Kubelet status updates on a still-pending pod must not reset the
        arrival stamp (the clock starts at FIRST ingest)."""
        cache = _mk_cache()
        pod = Pod(name="l0", namespace="ns", uid="ul0",
                  requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                  creation_index=1)
        cache.add_pod(pod)
        t0 = cache._arrival_ts["ns/l0"]
        update = Pod(name="l0", namespace="ns", uid="ul0",
                     requests={"cpu": 100.0}, phase=PodPhase.PENDING,
                     creation_index=1)
        cache.update_pod(update)
        assert cache._arrival_ts["ns/l0"] == t0


class TestPipelinedSim:
    def test_event_trigger_beats_fixed_tick_p99(self):
        """Virtual-time evidence for the acceptance bar: on a trigger-bound
        workload the event-driven loop's arrival→decision p99 beats the
        fixed 1 s tick by ≥ 2× (it is bounded by min_period, not the
        period), with zero duplicate binds and the same jobs completed."""
        from kube_batch_tpu.sim.runner import run_preset

        serial = run_preset("smoke", seed=3)
        pipe = run_preset("smoke", seed=3, pipelined=True)
        assert pipe["bind_integrity"]["duplicate_binds"] == 0
        assert pipe["invariants"]["errors"] == []
        assert pipe["jobs"] == serial["jobs"]
        p99_serial = serial["pod_bind_latency_vt"]["p99"]
        p99_pipe = pipe["pod_bind_latency_vt"]["p99"]
        assert p99_pipe * 2 <= p99_serial, (
            f"pipelined p99 {p99_pipe} not ≥2× better than serial "
            f"{p99_serial}"
        )
