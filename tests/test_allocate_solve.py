"""Kernel-level tests of the gang-constrained allocate solve.

These test *invariants*, not exact placements (SURVEY.md §7.3: the reference
randomizes tie-breaks itself, scheduler_helper.go:147-158): no node
overcommit, no committed partial gang, priority wins contention, overused
queues gain nothing.
"""

import numpy as np
import pytest

from kube_batch_tpu.api.cluster_info import ClusterInfo
from kube_batch_tpu.api.job_info import JobInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.pod import Node, Pod, PodGroup, Queue
from kube_batch_tpu.api.queue_info import QueueInfo
from kube_batch_tpu.api.resources import DEFAULT_SPEC
from kube_batch_tpu.api.snapshot import build_snapshot
from kube_batch_tpu.api.task_info import TaskInfo
from kube_batch_tpu.api.types import PodPhase
from kube_batch_tpu.ops.assignment import AllocateConfig, allocate_solve

GiB = 2**30


def build_cluster(nodes, jobs, queues=("default",)):
    """nodes: [(name, cpu_milli, mem)], jobs: [(name, queue, min_member,
    [(task, cpu, mem, prio)])]."""
    ci = ClusterInfo(DEFAULT_SPEC)
    for q in queues:
        name, weight = q if isinstance(q, tuple) else (q, 1)
        ci.queues[name] = QueueInfo(Queue(name=name, weight=weight))
    for name, cpu, mem in nodes:
        ni = NodeInfo(
            Node(name=name, allocatable={"cpu": cpu, "memory": mem, "pods": 110}),
            DEFAULT_SPEC,
        )
        ci.nodes[name] = ni
    for jname, queue, min_member, tasks in jobs:
        pg = PodGroup(name=jname, min_member=min_member, queue=queue)
        job = JobInfo(f"default/{jname}", DEFAULT_SPEC, pg)
        for tname, cpu, mem, prio in tasks:
            pod = Pod(name=f"{jname}-{tname}", requests={"cpu": cpu, "memory": mem},
                      priority=prio, phase=PodPhase.PENDING)
            job.add_task(TaskInfo(pod, DEFAULT_SPEC))
        ci.jobs[job.uid] = job
    return ci


def solve(ci, **kw):
    snap, meta = build_snapshot(ci)
    res = allocate_solve(snap, AllocateConfig(**kw))
    return snap, meta, res


def assert_no_overcommit(snap, res):
    assert np.all(np.asarray(res.node_idle) >= -np.asarray(snap.quanta)[None, :])
    assert np.all(np.asarray(res.node_releasing) >= -np.asarray(snap.quanta)[None, :])


class TestBasicAllocate:
    def test_single_job_fits(self):
        ci = build_cluster(
            nodes=[("n1", 4000, 8 * GiB)],
            jobs=[("j1", "default", 2, [(f"t{i}", 1000, 1 * GiB, 0) for i in range(2)])],
        )
        snap, meta, res = solve(ci)
        assigned = np.asarray(res.assigned)[: meta.n_tasks]
        assert np.all(assigned >= 0)
        assert not np.any(np.asarray(res.pipelined)[: meta.n_tasks])
        assert_no_overcommit(snap, res)

    def test_spreads_across_nodes_when_needed(self):
        # 4 tasks × 3000m on 2 × 8000m nodes → 2+2 split required
        ci = build_cluster(
            nodes=[("n1", 8000, 16 * GiB), ("n2", 8000, 16 * GiB)],
            jobs=[("j1", "default", 4, [(f"t{i}", 3000, 1 * GiB, 0) for i in range(4)])],
        )
        snap, meta, res = solve(ci)
        assigned = np.asarray(res.assigned)[: meta.n_tasks]
        assert np.all(assigned >= 0)
        counts = np.bincount(assigned, minlength=2)
        assert counts.max() == 2  # 3 × 3000m would overcommit
        assert_no_overcommit(snap, res)

    def test_padding_rows_never_assigned(self):
        ci = build_cluster(
            nodes=[("n1", 4000, 8 * GiB)],
            jobs=[("j1", "default", 1, [("t0", 1000, GiB, 0)])],
        )
        snap, meta, res = solve(ci)
        assert np.all(np.asarray(res.assigned)[meta.n_tasks:] == -1)


class TestGang:
    def test_partial_gang_discarded(self):
        # minMember=3 but capacity for 2 → nothing committed (Statement.Discard)
        ci = build_cluster(
            nodes=[("n1", 2000, 8 * GiB)],
            jobs=[("j1", "default", 3, [(f"t{i}", 1000, GiB, 0) for i in range(3)])],
        )
        snap, meta, res = solve(ci)
        assigned = np.asarray(res.assigned)[: meta.n_tasks]
        assert np.all(assigned == -1)
        assert not np.asarray(res.committed)[: meta.n_jobs].any()
        # idle fully restored
        np.testing.assert_allclose(
            np.asarray(res.node_idle), np.asarray(snap.node_idle)
        )

    def test_gang_off_commits_partial(self):
        ci = build_cluster(
            nodes=[("n1", 2000, 8 * GiB)],
            jobs=[("j1", "default", 3, [(f"t{i}", 1000, GiB, 0) for i in range(3)])],
        )
        snap, meta, res = solve(ci, gang=False)
        assigned = np.asarray(res.assigned)[: meta.n_tasks]
        assert (assigned >= 0).sum() == 2

    def test_discarded_gang_frees_resources_for_smaller_job(self):
        # big gang (min 4, only 3 fit) must not starve the small job (min 1)
        ci = build_cluster(
            nodes=[("n1", 3000, 8 * GiB)],
            jobs=[
                ("big", "default", 4, [(f"t{i}", 1000, GiB, 10) for i in range(4)]),
                ("small", "default", 1, [("t0", 1000, GiB, 0)]),
            ],
        )
        snap, meta, res = solve(ci)
        assigned = np.asarray(res.assigned)[: meta.n_tasks]
        job_of = np.asarray(snap.task_job)[: meta.n_tasks]
        big_idx = meta.job_uids.index("default/big")
        small_idx = meta.job_uids.index("default/small")
        assert np.all(assigned[job_of == big_idx] == -1)
        assert np.all(assigned[job_of == small_idx] >= 0)

    def test_two_gangs_contending(self):
        # two min=2 gangs, capacity 3 → exactly one gang commits fully
        ci = build_cluster(
            nodes=[("n1", 3000, 8 * GiB)],
            jobs=[
                ("a", "default", 2, [(f"t{i}", 1000, GiB, 0) for i in range(2)]),
                ("b", "default", 2, [(f"t{i}", 1000, GiB, 0) for i in range(2)]),
            ],
        )
        snap, meta, res = solve(ci)
        committed = np.asarray(res.committed)[: meta.n_jobs]
        assert committed.sum() == 1
        assigned = np.asarray(res.assigned)[: meta.n_tasks]
        job_of = np.asarray(snap.task_job)[: meta.n_tasks]
        winner = np.flatnonzero(committed)[0]
        assert (assigned[job_of == winner] >= 0).sum() == 2
        assert np.all(assigned[job_of != winner] == -1)


class TestPriorityAndFairness:
    def test_high_priority_job_wins_contention(self):
        ci = build_cluster(
            nodes=[("n1", 2000, 8 * GiB)],
            jobs=[
                ("lo", "default", 2, [(f"t{i}", 1000, GiB, 0) for i in range(2)]),
                ("hi", "default", 2, [(f"t{i}", 1000, GiB, 0) for i in range(2)]),
            ],
        )
        for uid, prio in [("default/lo", 1), ("default/hi", 100)]:
            ci.jobs[uid].priority = prio
        snap, meta, res = solve(ci)
        job_of = np.asarray(snap.task_job)[: meta.n_tasks]
        assigned = np.asarray(res.assigned)[: meta.n_tasks]
        hi = meta.job_uids.index("default/hi")
        assert np.all(assigned[job_of == hi] >= 0)
        assert np.all(assigned[job_of != hi] == -1)

    def test_proportion_shares_capacity_between_queues(self):
        # 2 queues, weight 1:1, cluster 4000m; each queue requests 4000m →
        # each deserves ~2000m → 2 tasks each
        ci = build_cluster(
            nodes=[("n1", 4000, 32 * GiB)],
            queues=[("qa", 1), ("qb", 1)],
            jobs=[
                ("ja", "qa", 1, [(f"t{i}", 1000, GiB, 0) for i in range(4)]),
                ("jb", "qb", 1, [(f"t{i}", 1000, GiB, 0) for i in range(4)]),
            ],
        )
        snap, meta, res = solve(ci)
        job_of = np.asarray(snap.task_job)[: meta.n_tasks]
        assigned = np.asarray(res.assigned)[: meta.n_tasks]
        ja = meta.job_uids.index("default/ja")
        a_placed = (assigned[job_of == ja] >= 0).sum()
        b_placed = (assigned[job_of != ja] >= 0).sum()
        assert a_placed == 2 and b_placed == 2

    def test_weighted_queues(self):
        # weight 3:1 over 4000m → 3000/1000 split
        ci = build_cluster(
            nodes=[("n1", 4000, 32 * GiB)],
            queues=[("qa", 3), ("qb", 1)],
            jobs=[
                ("ja", "qa", 1, [(f"t{i}", 1000, GiB, 0) for i in range(4)]),
                ("jb", "qb", 1, [(f"t{i}", 1000, GiB, 0) for i in range(4)]),
            ],
        )
        snap, meta, res = solve(ci)
        job_of = np.asarray(snap.task_job)[: meta.n_tasks]
        assigned = np.asarray(res.assigned)[: meta.n_tasks]
        ja = meta.job_uids.index("default/ja")
        assert (assigned[job_of == ja] >= 0).sum() == 3
        assert (assigned[job_of != ja] >= 0).sum() == 1


class TestDistributed:
    def test_initialize_noop_single_process(self):
        import jax

        from kube_batch_tpu.parallel.distributed import global_mesh, initialize
        initialize()  # single-process: must not raise
        assert jax.process_count() == 1
        mesh = global_mesh()
        # the global mesh spans EVERY visible device (the follower-host
        # contribution path)
        assert mesh.devices.size == len(jax.devices())
        assert mesh.axis_names == ("nodes",)


class TestShardedSolveAgreement:
    @pytest.mark.slow
    def test_sharded_solve_matches_single_device(self):
        """The mesh-sharded solve (node axis over 8 virtual devices,
        parallel/mesh.py) must produce EXACTLY the single-device assignment —
        GSPMD partitioning is an execution detail, not a semantic one."""
        import jax

        from kube_batch_tpu.parallel.mesh import make_mesh, sharded_allocate_solve
        from kube_batch_tpu.testing.synthetic import synthetic_device_snapshot

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual CPU mesh")
        snap, meta = synthetic_device_snapshot(n_tasks=2000, n_nodes=512,
                                               gang_size=5, n_queues=3)
        cfg = AllocateConfig()
        single = allocate_solve(snap, cfg)
        mesh = make_mesh(8)
        sharded = sharded_allocate_solve(snap, cfg, mesh)
        s_a = np.asarray(single.assigned)[: meta.n_tasks]
        m_a = np.asarray(sharded.assigned)[: meta.n_tasks]
        np.testing.assert_array_equal(s_a, m_a)
        np.testing.assert_array_equal(
            np.asarray(single.pipelined)[: meta.n_tasks],
            np.asarray(sharded.pipelined)[: meta.n_tasks],
        )
        np.testing.assert_allclose(
            np.asarray(single.node_idle), np.asarray(sharded.node_idle),
            rtol=1e-5, atol=1e-3,
        )
        assert (s_a >= 0).sum() > 0  # non-vacuous
        # the lazy fit-error histogram's sharded twin (failure cycles in
        # sharded mode dispatch it) must match the single-device one
        from kube_batch_tpu.ops.assignment import failure_histogram_solve
        from kube_batch_tpu.parallel.mesh import sharded_failure_histogram

        np.testing.assert_array_equal(
            np.asarray(failure_histogram_solve(snap)),
            np.asarray(sharded_failure_histogram(snap, mesh)),
        )


class TestOuterLoopContinuation:
    def test_capped_rounds_continue_across_outer_passes(self):
        """The outer while_loop must keep going when the bidding rounds hit
        their cap while still placing (regression: an early exit gated only
        on gang reverts dropped placeable tasks with rounds=1)."""
        n = 6
        ci = build_cluster(
            nodes=[(f"n{i}", 1000, 2 * GiB) for i in range(n)],
            jobs=[(f"j{i}", "default", 1, [("t", 1000, GiB, 0)])
                  for i in range(n)],
        )
        # rounds=1: every outer pass places at most one bidding round's worth;
        # with identical scores the argmax herds and conflicts leave tasks
        # unplaced each round — only outer continuation finishes the set
        snap, meta, res = solve(ci, rounds=1, outer=8)
        assigned = np.asarray(res.assigned)[: meta.n_tasks]
        assert (assigned >= 0).all(), assigned
        assert_no_overcommit(snap, res)


def _prefer_last_node_row(snap):
    """Module-level custom score row (jit-cache friendly): strongly prefer
    the highest live node index."""
    import jax.numpy as jnp

    N = snap.node_alloc.shape[0]
    col = jnp.where(snap.node_valid, jnp.arange(N, dtype=jnp.float32), 0.0)
    return jnp.broadcast_to(col[None, :], (snap.task_req.shape[0], N)) * 100.0


class TestScoreRowExtensionSeam:
    def test_custom_row_changes_placement(self):
        """The session_plugins.go:392-492 extension surface at the tensor
        level: a registered device score row must actually steer the solve."""
        from kube_batch_tpu.ops.scoring import ScoreWeights

        ci = build_cluster(
            nodes=[(f"n{i}", 64000, 64 * GiB) for i in range(4)],
            jobs=[(f"j{i}", "default", 1, [("t", 1000, GiB, 0)])
                  for i in range(8)],
        )
        # baseline: least-requested spreads the 8 tasks across empty nodes
        snap, meta, base = solve(ci)
        base_nodes = set(np.asarray(base.assigned)[: meta.n_tasks].tolist())
        assert len(base_nodes) > 1

        ci2 = build_cluster(
            nodes=[(f"n{i}", 64000, 64 * GiB) for i in range(4)],
            jobs=[(f"j{i}", "default", 1, [("t", 1000, GiB, 0)])
                  for i in range(8)],
        )
        snap2, meta2, custom = solve(
            ci2,
            weights=ScoreWeights(
                extra_rows=(("prefer-last", _prefer_last_node_row, 1.0),)
            ),
        )
        assigned = np.asarray(custom.assigned)[: meta2.n_tasks]
        # the custom row dominates the bounded 0..10 built-ins: every task
        # lands on the last live node (it has capacity for all 8)
        last = max(
            int(i) for i, name in enumerate(meta2.node_names)
            if name
        )
        assert np.all(assigned == last), assigned
        assert_no_overcommit(snap2, custom)

    def test_session_level_registration(self):
        """A plugin registering through Session.add_score_row changes real
        action placement end-to-end."""
        from kube_batch_tpu import actions as _a  # noqa: F401
        from kube_batch_tpu import plugins as _p  # noqa: F401
        from kube_batch_tpu.framework.conf import load_scheduler_conf
        from kube_batch_tpu.framework.interface import get_action
        from kube_batch_tpu.framework.session import close_session, open_session
        from kube_batch_tpu.testing.synthetic import synthetic_cluster

        cache = synthetic_cluster(n_tasks=16, n_nodes=4, gang_size=1, n_queues=1)
        conf = load_scheduler_conf(None)
        ssn = open_session(cache, conf.tiers)
        ssn.add_score_row("prefer-last", _prefer_last_node_row, weight=1.0)
        get_action("allocate").execute(ssn)
        close_session(ssn)
        cache.flush_binds()
        hosts = set(cache.binder.binds.values())
        # every task funneled onto one node (nodes are big enough)
        assert hosts == {"n3"}, hosts
