"""Delta-vs-full-rebuild equivalence over randomized churn.

The cross-cycle machinery (cache/dirty.py, columns.sync_session_rows, the
per-cycle device-resident cache) promises BIT-EXACT equivalence with the
from-scratch path.  These tests churn a real SchedulerCache through the
ordinary ingest surface — gang arrivals, completions, status flips, node
crashes/rejoins, queue and priority-class changes — run real scheduling
cycles, and after every cycle compare the delta-built device snapshot (and
the session-open state) against a forced full rebuild.
"""

from __future__ import annotations

import numpy as np
import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.pod import (
    GROUP_NAME_ANNOTATION,
    Node,
    Pod,
    PodGroup,
    PriorityClass,
    Queue,
)
from kube_batch_tpu.api.snapshot import DeviceSnapshot
from kube_batch_tpu.api.types import PodPhase
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.framework.conf import load_scheduler_conf
from kube_batch_tpu.framework.interface import get_action
from kube_batch_tpu.framework.session import close_session, open_session
from kube_batch_tpu.sim import kubelet as kl
from kube_batch_tpu.testing.synthetic import GiB


def _mk_cache(n_nodes=6, n_queues=2):
    cache = SchedulerCache()
    for q in range(n_queues):
        cache.add_queue(Queue(name=f"q{q}", uid=f"uq{q}", weight=q + 1))
    for i in range(n_nodes):
        cache.add_node(Node(
            name=f"n{i}",
            allocatable={"cpu": 16000.0, "memory": 64 * GiB, "pods": 110.0},
        ))
    return cache


class _Churner:
    """Randomized but seed-deterministic cluster churn through the real
    ingest handlers."""

    def __init__(self, cache, rng, n_queues=2):
        self.cache = cache
        self.rng = rng
        self.n_queues = n_queues
        self.serial = 0
        self.gangs = []  # job names with live pods

    def add_gang(self, size=None):
        self.serial += 1
        g = f"g{self.serial}"
        size = size or int(self.rng.integers(1, 4))
        self.cache.add_pod_group(PodGroup(
            name=g, namespace="churn", uid=f"pg-{g}", min_member=size,
            queue=f"q{int(self.rng.integers(self.n_queues))}",
            creation_index=self.serial,
        ))
        for k in range(size):
            self.cache.add_pod(Pod(
                name=f"{g}-{k}", namespace="churn", uid=f"pod-{g}-{k}",
                requests={"cpu": float(self.rng.choice([250.0, 500.0, 1000.0])),
                          "memory": 1 * GiB},
                annotations={GROUP_NAME_ANNOTATION: g},
                phase=PodPhase.PENDING,
                creation_index=self.serial * 100 + k,
            ))
        self.gangs.append(g)

    def complete_gang(self):
        if not self.gangs:
            return
        g = self.gangs.pop(int(self.rng.integers(len(self.gangs))))
        job_uid = f"churn/{g}"
        job = self.cache.jobs.get(job_uid)
        keys = sorted(job.tasks.keys()) if job is not None else []
        for key in keys:
            kl.delete_pod(self.cache, key)
        self.cache.delete_pod_group(job_uid)

    def flip_statuses(self):
        # bound pods progress to Running/Succeeded like a kubelet would
        pods = [p for p in self.cache.pods.values() if p.node_name]
        if not pods:
            return
        pods.sort(key=lambda p: p.key())
        for p in pods[: int(self.rng.integers(1, 3))]:
            if p.phase == PodPhase.PENDING:
                kl.set_running(self.cache, p.key(), p.node_name)
            elif p.phase == PodPhase.RUNNING and self.rng.random() < 0.5:
                kl.set_succeeded(self.cache, p.key())

    def node_churn(self):
        r = self.rng.random()
        if r < 0.5:
            self.cache.delete_node(f"n{int(self.rng.integers(3))}")
        else:
            i = int(self.rng.integers(3))
            self.cache.add_node(Node(
                name=f"n{i}",
                allocatable={"cpu": 16000.0, "memory": 64 * GiB,
                             "pods": 110.0},
            ))

    def step(self):
        r = self.rng.random()
        if r < 0.45:
            self.add_gang()
        elif r < 0.70:
            self.complete_gang()
        elif r < 0.90:
            self.flip_statuses()
        else:
            self.node_churn()


def _snapshot_arrays(snap: DeviceSnapshot) -> dict:
    return {f: np.array(getattr(snap, f)) for f in snap._fields}


def _assert_snaps_equal(delta: dict, full: dict, context: str):
    for field, want in full.items():
        got = delta[field]
        assert got.shape == want.shape, f"{context}: {field} shape"
        assert np.array_equal(got, want), (
            f"{context}: field {field} diverged between delta and full "
            f"rebuild (rows {np.flatnonzero(np.any(np.atleast_2d(got != want), axis=-1))[:8]})"
        )


@pytest.mark.parametrize("seed", [0, 7, 23])
def test_delta_device_snapshot_bit_exact_under_churn(seed):
    """Over randomized churn sequences, the delta-built device snapshot is
    bit-exact against a from-scratch row rescan every cycle (acceptance
    criterion of the cross-cycle resident-snapshot PR)."""
    rng = np.random.default_rng(seed)
    cache = _mk_cache()
    conf = load_scheduler_conf(None)
    churn = _Churner(cache, rng)
    for _ in range(4):
        churn.add_gang()
    delta_cycles = 0
    for cycle in range(14):
        churn.step()
        ssn = open_session(cache, conf.tiers)
        cols = cache.columns
        try:
            snap, _meta = cols.device_snapshot(ssn)
            got = _snapshot_arrays(snap)
            path = cols.last_snapshot_path
            delta_cycles += path == "delta"
            # force the full rescan over the same session state and compare
            cols.sync_session_rows(ssn)
            snap_full, _ = cols.device_snapshot(ssn)
            _assert_snaps_equal(
                got, _snapshot_arrays(snap_full),
                f"seed={seed} cycle={cycle} path={path}",
            )
            for name in conf.actions:
                get_action(name).execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()
    assert cache.columns.check_consistency(cache) == []
    # the delta path must actually engage (else this test proves nothing)
    assert delta_cycles >= 5, f"delta path engaged only {delta_cycles}x"


@pytest.mark.parametrize("seed", [3, 11])
def test_delta_open_state_matches_full_view(seed):
    """The delta session open hands out exactly the membership, priorities,
    and at-open PodGroup statuses a full session_view would derive."""
    rng = np.random.default_rng(seed)
    cache = _mk_cache()
    cache.add_priority_class(PriorityClass(name="high", value=50))
    conf = load_scheduler_conf(None)
    churn = _Churner(cache, rng)
    for _ in range(3):
        churn.add_gang()
    for cycle in range(10):
        churn.step()
        ssn = open_session(cache, conf.tiers)
        try:
            # expected: re-derive the full view against the SAME live state
            # (session_view only reads; the exclusive gate is already held)
            expected = cache.session_view()
            assert set(ssn.jobs) | {j.uid for j in ssn.gate_dropped_jobs} \
                == set(expected.jobs), f"cycle {cycle} membership"
            for uid, job in expected.jobs.items():
                assert job.priority == expected.jobs[uid].priority
            expected_status = {
                uid: (j.pod_group.phase, j.pod_group.running,
                      j.pod_group.failed, j.pod_group.succeeded)
                for uid, j in expected.jobs.items() if j.pod_group is not None
            }
            assert ssn.pod_group_status_at_open == expected_status, (
                f"cycle {cycle} at-open status"
            )
            for name in conf.actions:
                get_action(name).execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()


def test_per_cycle_device_cache_round_trips_bit_exact():
    """The scatter-refreshed device-resident per-cycle columns fetch back
    bit-identical to the host columns after every churn cycle."""
    from kube_batch_tpu.api.resident import PER_CYCLE_FIELDS

    rng = np.random.default_rng(5)
    cache = _mk_cache()
    # realistic axis capacities: with micro columns the cache rightly
    # prefers whole-column re-uploads (cheaper than the smallest fixed
    # scatter payload) and the delta path under test would never engage.
    # Node axis stays below SHARD_MIN_NODES so the actions keep the
    # single-device dispatch this test exercises
    cache.columns.reserve(n_tasks=2048, n_nodes=128, n_jobs=512)
    conf = load_scheduler_conf(None)
    churn = _Churner(cache, rng)
    for _ in range(3):
        churn.add_gang()
    cols = cache.columns
    for cycle in range(8):
        churn.step()
        ssn = open_session(cache, conf.tiers)
        try:
            snap, _meta = cols.device_snapshot(ssn)
            swapped = cols.per_cycle_resident(snap)
            for field in PER_CYCLE_FIELDS:
                host = np.asarray(getattr(snap, field))
                dev = np.asarray(getattr(swapped, field))
                assert np.array_equal(host, dev), (
                    f"cycle {cycle}: device-resident {field} diverged"
                )
            for name in conf.actions:
                get_action(name).execute(ssn)
        finally:
            close_session(ssn)
        cache.flush_binds()
    pcd = cols._per_cycle_dev.get(None)
    assert pcd is not None and pcd.scatter_updates > 0, (
        "scatter-delta path never engaged"
    )


def test_full_fallback_on_row_space_changes():
    """Queue and priority-class changes invalidate the delta path for one
    open (row spaces / priority resolution are global inputs)."""
    cache = _mk_cache()
    conf = load_scheduler_conf(None)
    churn = _Churner(cache, np.random.default_rng(1))
    churn.add_gang()

    def one_open():
        ssn = open_session(cache, conf.tiers)
        close_session(ssn)
        return cache.last_open_path

    assert one_open() == "full"      # cold cache
    churn.add_gang()
    assert one_open() == "delta"     # low churn
    cache.add_queue(Queue(name="q9", uid="uq9", weight=1))
    assert one_open() == "full"      # queue row space moved
    assert one_open() == "delta"
    cache.add_priority_class(PriorityClass(name="p", value=9))
    assert one_open() == "full"      # priority universe moved
    assert one_open() == "delta"


def test_delta_disabled_forces_full_path():
    cache = _mk_cache()
    cache.delta_enabled = False
    conf = load_scheduler_conf(None)
    churn = _Churner(cache, np.random.default_rng(2))
    churn.add_gang()
    for _ in range(3):
        ssn = open_session(cache, conf.tiers)
        close_session(ssn)
        assert cache.last_open_path == "full"
        assert cache.columns.last_snapshot_path == "full"


def test_close_session_delta_matches_full_rebuild(monkeypatch):
    """The delta close-status pass (visit only touched/need-record rows,
    qcounts off the j_phase column) must leave byte-identical end state to
    the forced full visit (KB_DELTA_CLOSE=0): PodGroup phases/counts,
    recorded events, and QueueStatus writes, over randomized churn."""

    def run(delta_close: bool, seed=13, cycles=10):
        if delta_close:
            monkeypatch.delenv("KB_DELTA_CLOSE", raising=False)
        else:
            monkeypatch.setenv("KB_DELTA_CLOSE", "0")
        rng = np.random.default_rng(seed)
        cache = _mk_cache()
        conf = load_scheduler_conf(None)
        churn = _Churner(cache, rng)
        for _ in range(4):
            churn.add_gang()
        states = []
        for _ in range(cycles):
            churn.step()
            ssn = open_session(cache, conf.tiers)
            try:
                for name in conf.actions:
                    get_action(name).execute(ssn)
            finally:
                close_session(ssn)
            cache.flush_binds()
            states.append({
                uid: (j.pod_group.phase, j.pod_group.running,
                      j.pod_group.failed, j.pod_group.succeeded)
                for uid, j in sorted(cache.jobs.items())
                if j.pod_group is not None
            })
            states.append(
                {q: dict(c) for q, c in
                 sorted(cache._queue_status_written.items())}
            )
        events = list(cache.events)
        assert cache.columns.check_consistency(cache) == []
        cache.stop()
        return states, events

    delta_states, delta_events = run(True)
    full_states, full_events = run(False)
    assert delta_states == full_states
    assert delta_events == full_events


def test_stale_fit_state_cleared_across_delta_opens():
    """A job that recorded fit errors in one cycle starts the next session
    clean even when the open takes the delta path (note_fit_state feeds the
    targeted clearing set)."""
    cache = _mk_cache()
    conf = load_scheduler_conf(None)
    churn = _Churner(cache, np.random.default_rng(4))
    churn.add_gang(size=2)
    ssn = open_session(cache, conf.tiers)
    job = next(iter(ssn.jobs.values()))
    job.job_fit_errors = "synthetic"
    from kube_batch_tpu.api.job_info import FitErrors

    fe = FitErrors()
    fe.set_histogram({"synthetic reason": 1}, 1)
    job.nodes_fit_errors["t"] = fe
    ssn.note_fit_state(job)
    close_session(ssn)
    ssn = open_session(cache, conf.tiers)
    try:
        assert cache.last_open_path == "delta"
        refreshed = ssn.jobs.get(job.uid)
        assert refreshed is None or refreshed.job_fit_errors == ""
        assert refreshed is None or refreshed.nodes_fit_errors == {}
    finally:
        close_session(ssn)
