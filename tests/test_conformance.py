"""Conformance scenarios — the rebuild's analog of the reference e2e suite
(test/e2e/job.go, predicates.go, nodeorder.go, queue.go; SURVEY.md §4.2).

Each test is a behavioral spec of the whole scheduler run against the fake
backend: synthetic objects through the real cache handlers, real session +
actions, assertions on captured binds/evicts. Invariant-style where the
reference's own placement is randomized (scheduler_helper.go:147-158)."""

import pytest

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.pod import (
    Affinity,
    PodGroup,
    PriorityClass,
    Queue,
    Taint,
    Toleration,
)
from kube_batch_tpu.api.types import PodGroupPhase, PodPhase

from tests.fixtures import GiB, build_cache, build_node, build_pod
from tests.test_actions import run_actions


def _cache_with_pv_binder(**kw):
    """build_cache with the real PV ledger behind the volume seams."""
    from kube_batch_tpu.cache.volume import StandalonePVBinder

    cache = build_cache(**kw)
    cache.volume_binder = StandalonePVBinder()
    return cache


def gang(cache_kw_pods, name, n, cpu=1000, queue="default", priority=0, **pod_kw):
    """Append n pending gang pods for PodGroup `name` to a pod list."""
    for i in range(n):
        cache_kw_pods.append(
            build_pod("c1", f"{name}-{i}", None, PodPhase.PENDING,
                      {"cpu": cpu, "memory": GiB}, group_name=name,
                      priority=priority, **pod_kw)
        )


class TestJobScenarios:
    def test_schedule_multiple_jobs(self):
        """job.go:48 Schedule Multiple Jobs: several gangs co-scheduled."""
        pods = []
        for j in range(3):
            gang(pods, f"job{j}", 2)
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name=f"job{j}", namespace="c1", min_member=2,
                                 queue="default") for j in range(3)],
            nodes=[build_node("n1", cpu=4000, mem=16 * GiB),
                   build_node("n2", cpu=4000, mem=16 * GiB)],
            pods=pods,
        )
        run_actions(cache)
        assert len(cache.binder.binds) == 6

    def test_gang_full_occupied_cluster_binds_nothing(self):
        """job.go:118 Gang: Full Occupied: no partial gang on a full cluster."""
        pods = [
            build_pod("c1", f"run-{i}", "n1", PodPhase.RUNNING,
                      {"cpu": 1000, "memory": GiB})
            for i in range(4)
        ]
        gang(pods, "starved", 2)
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="starved", namespace="c1", min_member=2,
                                 queue="default")],
            nodes=[build_node("n1", cpu=4000, mem=16 * GiB)],
            pods=pods,
        )
        run_actions(cache)
        assert cache.binder.binds == {}
        job = cache.jobs["c1/starved"]
        assert any(c.type == "Unschedulable" for c in job.pod_group.conditions)

    def test_gang_unsatisfied_releases_resources_to_other_job(self):
        """job.go:149 Gang: an unsatisfiable gang must not hold resources a
        satisfiable gang needs (the Statement discard, statement.go:309)."""
        pods = []
        gang(pods, "big", 3)    # needs 3×1000m — cluster only has 2000m
        gang(pods, "small", 2)  # needs 2×1000m — fits iff big released
        cache = build_cache(
            queues=["default"],
            pod_groups=[
                PodGroup(name="big", namespace="c1", min_member=3, queue="default"),
                PodGroup(name="small", namespace="c1", min_member=2, queue="default"),
            ],
            nodes=[build_node("n1", cpu=2000, mem=16 * GiB)],
            pods=pods,
        )
        run_actions(cache)
        assert set(cache.binder.binds) == {"c1/small-0", "c1/small-1"}

    def test_fit_unassigned_task_counts_toward_gang(self):
        """job.go:369: a task already bound counts toward minMember; only the
        remainder schedules."""
        pods = [
            build_pod("c1", "pre-0", "n1", PodPhase.RUNNING,
                      {"cpu": 1000, "memory": GiB}, group_name="pg"),
        ]
        gang(pods, "rest", 1)
        pods[-1].annotations = dict(pods[-1].annotations)
        # put the pending pod in the same podgroup
        from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION
        pods[-1].annotations[GROUP_NAME_ANNOTATION] = "pg"
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg", namespace="c1", min_member=2,
                                 queue="default")],
            nodes=[build_node("n1", cpu=4000, mem=16 * GiB)],
            pods=pods,
        )
        run_actions(cache)
        assert set(cache.binder.binds) == {"c1/rest-0"}

    def test_task_priority_placed_first_under_scarcity(self):
        """job.go:329 TaskPriority: within a job, high-priority tasks win the
        scarce capacity (priority plugin TaskOrderFn, priority.go:40-60)."""
        pods = []
        gang(pods, "lo", 2, priority=1)
        gang(pods, "hi", 2, priority=100)
        # one job containing both priority bands
        from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION
        for p in pods:
            p.annotations[GROUP_NAME_ANNOTATION] = "mixed"
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="mixed", namespace="c1", min_member=2,
                                 queue="default")],
            nodes=[build_node("n1", cpu=2000, mem=16 * GiB)],
            pods=pods,
        )
        run_actions(cache)
        assert set(cache.binder.binds) == {"c1/hi-0", "c1/hi-1"}

    def test_job_priority_wins_scarce_capacity(self):
        """job.go:410 Job Priority: the high-PriorityClass job gets the
        cluster; the low one starves."""
        pods = []
        gang(pods, "low", 2)
        gang(pods, "high", 2)
        cache = build_cache(
            queues=["default"],
            pod_groups=[
                PodGroup(name="low", namespace="c1", min_member=2, queue="default"),
                PodGroup(name="high", namespace="c1", min_member=2, queue="default",
                         priority_class="prio-100"),
            ],
            nodes=[build_node("n1", cpu=2000, mem=16 * GiB)],
            pods=pods,
        )
        cache.add_priority_class(PriorityClass(name="prio-100", value=100))
        run_actions(cache)
        assert set(cache.binder.binds) == {"c1/high-0", "c1/high-1"}

    def test_multiple_preemption(self):
        """job.go:221 Multiple Preemption: two starved preemptors evict two
        running victims."""
        pods = [
            build_pod("c1", f"victim-{i}", "n1", PodPhase.RUNNING,
                      {"cpu": 1000, "memory": GiB}, group_name="lowjob")
            for i in range(3)
        ]
        gang(pods, "high", 2, priority=100)
        cache = build_cache(
            queues=["default"],
            pod_groups=[
                PodGroup(name="lowjob", namespace="c1", min_member=1, queue="default"),
                PodGroup(name="high", namespace="c1", min_member=2, queue="default",
                         priority_class="prio-100"),
            ],
            nodes=[build_node("n1", cpu=3000, mem=16 * GiB)],
            pods=pods,
        )
        cache.add_priority_class(PriorityClass(name="prio-100", value=100))
        run_actions(cache, action_names=["preempt"])
        assert len(cache.evictor.evicts) == 2
        assert all(k.startswith("c1/victim-") for k in cache.evictor.evicts)

    def test_proportion_weighted_split(self):
        """job.go:458 Proportion: 3:1 weighted queues split a 4000m cluster
        3000/1000 (proportion.go:101-154)."""
        pods = []
        for i in range(8):
            pods.append(build_pod("c1", f"a-{i}", None, PodPhase.PENDING,
                                  {"cpu": 500, "memory": GiB // 2}, group_name=f"ja{i}"))
        for i in range(8):
            pods.append(build_pod("c1", f"b-{i}", None, PodPhase.PENDING,
                                  {"cpu": 500, "memory": GiB // 2}, group_name=f"jb{i}"))
        cache = build_cache(
            queues=[Queue(name="qa", weight=3), Queue(name="qb", weight=1)],
            pod_groups=(
                [PodGroup(name=f"ja{i}", namespace="c1", min_member=1, queue="qa")
                 for i in range(8)]
                + [PodGroup(name=f"jb{i}", namespace="c1", min_member=1, queue="qb")
                   for i in range(8)]
            ),
            nodes=[build_node("n1", cpu=4000, mem=16 * GiB)],
            pods=pods,
        )
        run_actions(cache, action_names=["allocate"])
        a_binds = sum(1 for k in cache.binder.binds if k.startswith("c1/a-"))
        b_binds = sum(1 for k in cache.binder.binds if k.startswith("c1/b-"))
        assert a_binds == 6, cache.binder.binds
        assert b_binds == 2, cache.binder.binds


class TestPredicateScenarios:
    def test_node_affinity_required_term(self):
        """predicates.go e2e:35 NodeAffinity: required In-term steers the pod."""
        cache = build_cache(
            queues=["default"],
            nodes=[
                build_node("east", labels={"zone": "us-east"}),
                build_node("west", labels={"zone": "us-west"}),
            ],
            pods=[
                build_pod("c1", "pinned", None, PodPhase.PENDING,
                          {"cpu": 500, "memory": GiB},
                          affinity=Affinity(node_terms=[[("zone", "In", ("us-east",))]])),
            ],
        )
        run_actions(cache)
        assert cache.binder.binds == {"c1/pinned": "east"}

    def test_node_affinity_multi_term_or(self):
        """Multi-term affinity (OR) is host-validated: the device proposal is
        re-checked through the predicates plugin in the allocate replay."""
        cache = build_cache(
            queues=["default"],
            nodes=[
                build_node("a", labels={"zone": "z1"}),
                build_node("b", labels={"zone": "z2"}),
                build_node("c", labels={"zone": "z3"}),
            ],
            pods=[
                build_pod("c1", "either", None, PodPhase.PENDING,
                          {"cpu": 500, "memory": GiB},
                          affinity=Affinity(node_terms=[
                              [("zone", "In", ("z1",))],
                              [("zone", "In", ("z2",))],
                          ])),
            ],
        )
        # device can't encode the OR; run enough cycles for the host net to
        # land it (each cycle re-proposes; the accept set shrinks to legal)
        for _ in range(4):
            run_actions(cache)
            if cache.binder.binds:
                break
        assert list(cache.binder.binds.values())[0] in ("a", "b")

    def test_hostport_conflict(self):
        """predicates.go e2e:84 Hostport: two pods wanting the same host port
        land on different nodes."""
        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1"), build_node("n2")],
            pods=[
                build_pod("c1", "web-0", None, PodPhase.PENDING,
                          {"cpu": 500, "memory": GiB}, host_ports=(8080,)),
                build_pod("c1", "web-1", None, PodPhase.PENDING,
                          {"cpu": 500, "memory": GiB}, host_ports=(8080,)),
            ],
        )
        for _ in range(4):
            run_actions(cache)
            if len(cache.binder.binds) == 2:
                break
        assert len(cache.binder.binds) == 2
        assert cache.binder.binds["c1/web-0"] != cache.binder.binds["c1/web-1"]

    def test_hostport_blocked_by_resident(self):
        """A resident pod's host port blocks the only node — the pending
        claimant must stay unbound (exercises the port index the vectorized
        fallback placement consults)."""
        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1")],
            pods=[
                build_pod("c1", "resident", "n1", PodPhase.RUNNING,
                          {"cpu": 500, "memory": GiB}, host_ports=(9090,)),
                build_pod("c1", "wants", None, PodPhase.PENDING,
                          {"cpu": 500, "memory": GiB}, host_ports=(9090,)),
            ],
        )
        run_actions(cache)
        assert "c1/wants" not in cache.binder.binds

    def test_hostport_gangs_promoted_to_bulk(self):
        """Conflict-free ported gangs take the bulk path (ports promotion);
        placements stay correct and port-exclusive per node."""
        cache = build_cache(
            queues=["default"],
            pod_groups=[
                PodGroup(name=f"g{j}", namespace="c1", min_member=2,
                         queue="default") for j in range(4)
            ],
            nodes=[build_node(f"n{i}", pods=4) for i in range(8)],
            pods=[
                build_pod("c1", f"g{j}-{i}", None, PodPhase.PENDING,
                          {"cpu": 500, "memory": GiB}, group_name=f"g{j}",
                          host_ports=(7000 + j,))
                for j in range(4) for i in range(2)
            ],
        )
        run_actions(cache)
        assert len(cache.binder.binds) == 8
        # no two pods sharing a port landed on the same node
        seen = {}
        for j in range(4):
            for i in range(2):
                node = cache.binder.binds[f"c1/g{j}-{i}"]
                assert (node, 7000 + j) not in seen
                seen[(node, 7000 + j)] = True
        from kube_batch_tpu.framework.interface import get_action

        fb = get_action("allocate").last_fallback
        assert fb["promoted_ports_jobs"] >= 1, fb

    def test_memory_pressure_gate_excludes_node(self):
        """predicates.go:233-276 pressure gates, enabled via plugin args:
        a MemoryPressure node is excluded and the placement still rides the
        fast (device) path — no job is demoted to the host replay for it."""
        from kube_batch_tpu.framework.conf import parse_scheduler_conf
        from kube_batch_tpu.framework.interface import get_action

        conf = parse_scheduler_conf("""
actions: "allocate, backfill"
tiers:
- plugins:
  - name: gang
  - name: predicates
    arguments:
      predicate.MemoryPressureEnable: "true"
""")
        cache = build_cache(
            queues=["default"],
            nodes=[
                build_node("pressured", conditions={"MemoryPressure": True}),
                build_node("healthy"),
            ],
            pods=[build_pod("c1", "p0", None, PodPhase.PENDING,
                            {"cpu": 500, "memory": GiB})],
        )
        from kube_batch_tpu.scheduler import Scheduler

        Scheduler(cache, conf=conf).run_once()
        cache.flush_binds()
        assert cache.binder.binds == {"c1/p0": "healthy"}
        fb = get_action("allocate").last_fallback
        assert fb["slow_jobs"] == 0, fb  # pressure no longer demotes jobs
        assert not cache.columns.check_consistency(cache)

    def test_taints_block_untolerated(self):
        """predicates.go e2e:161 Taints/Tolerations."""
        cache = build_cache(
            queues=["default"],
            nodes=[
                build_node("tainted", taints=[Taint(key="dedicated", value="ml",
                                                    effect="NoSchedule")]),
                build_node("open"),
            ],
            pods=[
                build_pod("c1", "plain", None, PodPhase.PENDING,
                          {"cpu": 500, "memory": GiB}),
                build_pod("c1", "tolerant", None, PodPhase.PENDING,
                          {"cpu": 500, "memory": GiB},
                          tolerations=[Toleration(key="dedicated", value="ml",
                                                  effect="NoSchedule")]),
            ],
        )
        run_actions(cache)
        assert cache.binder.binds["c1/plain"] == "open"
        assert "c1/tolerant" in cache.binder.binds  # either node is legal

    def test_max_pods_respected(self):
        """predicates.go e2e:209 MaxPods: the pods capacity dimension caps
        placements per node."""
        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1", cpu=64000, mem=64 * GiB, pods=2)],
            pods=[
                build_pod("c1", f"p{i}", None, PodPhase.PENDING,
                          {"cpu": 100, "memory": GiB // 4})
                for i in range(5)
            ],
        )
        run_actions(cache)
        assert len(cache.binder.binds) == 2

    def test_unschedulable_node_excluded(self):
        """CheckNodeUnschedulable (predicates.go:181-192)."""
        cache = build_cache(
            queues=["default"],
            nodes=[
                build_node("cordoned", unschedulable=True),
                build_node("open"),
            ],
            pods=[build_pod("c1", "p0", None, PodPhase.PENDING,
                            {"cpu": 500, "memory": GiB})],
        )
        run_actions(cache)
        assert cache.binder.binds == {"c1/p0": "open"}

    def test_not_ready_node_excluded_from_snapshot(self):
        """cache.go:595-597: NotReady nodes never enter the snapshot."""
        cache = build_cache(
            queues=["default"],
            nodes=[build_node("down", ready=False)],
            pods=[build_pod("c1", "p0", None, PodPhase.PENDING,
                            {"cpu": 500, "memory": GiB})],
        )
        run_actions(cache)
        assert cache.binder.binds == {}


class TestNodeOrderScenarios:
    def test_least_requested_spreads(self):
        """nodeorder.go e2e:138 Least Requested: a new pod prefers the idler
        node."""
        cache = build_cache(
            queues=["default"],
            nodes=[build_node("busy", cpu=8000, mem=16 * GiB),
                   build_node("idle", cpu=8000, mem=16 * GiB)],
            pods=[
                build_pod("c1", "resident", "busy", PodPhase.RUNNING,
                          {"cpu": 6000, "memory": 8 * GiB}),
                build_pod("c1", "new", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}),
            ],
        )
        run_actions(cache)
        assert cache.binder.binds == {"c1/new": "idle"}

    def test_binpack_packs_when_weighted(self):
        """The binpack row (BASELINE north star): with binpack outweighing
        leastrequested, the new pod packs onto the busier node."""
        conf = """
actions: "allocate"
tiers:
- plugins:
  - name: gang
  - name: binpack
    arguments:
      binpack.weight: 10
"""
        cache = build_cache(
            queues=["default"],
            nodes=[build_node("busy", cpu=8000, mem=16 * GiB),
                   build_node("idle", cpu=8000, mem=16 * GiB)],
            pods=[
                build_pod("c1", "resident", "busy", PodPhase.RUNNING,
                          {"cpu": 6000, "memory": 8 * GiB}),
                build_pod("c1", "new", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}),
            ],
        )
        run_actions(cache, conf_text=conf)
        assert cache.binder.binds == {"c1/new": "busy"}


class TestStatementScenario:
    def test_statement_discard_restores_state(self):
        """job.go:292 Statement: allocate then discard leaves no trace."""
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg", namespace="c1", min_member=1,
                                 queue="default")],
            nodes=[build_node("n1", cpu=4000, mem=8 * GiB)],
            pods=[build_pod("c1", "p0", None, PodPhase.PENDING,
                            {"cpu": 1000, "memory": GiB}, group_name="pg")],
        )
        from kube_batch_tpu.framework.conf import parse_scheduler_conf
        from kube_batch_tpu.framework.session import close_session, open_session
        from kube_batch_tpu.api.types import TaskStatus

        conf = parse_scheduler_conf(
            'actions: "allocate"\ntiers:\n- plugins:\n  - name: gang\n')
        ssn = open_session(cache, conf.tiers)
        job = next(iter(ssn.jobs.values()))
        task = next(iter(job.tasks.values()))
        node = ssn.nodes["n1"]
        idle_before = node.idle.vec.copy()

        stmt = ssn.statement()
        stmt.allocate(task, "n1")
        assert task.status == TaskStatus.ALLOCATED
        assert node.idle.vec[0] == idle_before[0] - 1000
        stmt.discard()
        assert task.status == TaskStatus.PENDING
        assert node.idle.vec[0] == idle_before[0]
        assert cache.binder.binds == {}
        close_session(ssn)

    def test_statement_commit_binds(self):
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg", namespace="c1", min_member=1,
                                 queue="default")],
            nodes=[build_node("n1", cpu=4000, mem=8 * GiB)],
            pods=[build_pod("c1", "p0", None, PodPhase.PENDING,
                            {"cpu": 1000, "memory": GiB}, group_name="pg")],
        )
        from kube_batch_tpu.framework.conf import parse_scheduler_conf
        from kube_batch_tpu.framework.session import close_session, open_session

        conf = parse_scheduler_conf(
            'actions: "allocate"\ntiers:\n- plugins:\n  - name: gang\n')
        ssn = open_session(cache, conf.tiers)
        job = next(iter(ssn.jobs.values()))
        task = next(iter(job.tasks.values()))
        stmt = ssn.statement()
        stmt.allocate(task, "n1")
        stmt.commit()
        assert cache.binder.binds == {"c1/p0": "n1"}
        close_session(ssn)


class TestReclaimScenario:
    def test_reclaim_respects_deserved(self):
        """queue.go e2e:26: reclaim only down to the victim queue's deserved
        share (proportion.go:171-196)."""
        pods = [
            build_pod("c1", f"a-{i}", "n1", PodPhase.RUNNING,
                      {"cpu": 1000, "memory": GiB}, group_name="ja")
            for i in range(4)
        ]
        pods.append(build_pod("c1", "b-0", None, PodPhase.PENDING,
                              {"cpu": 1000, "memory": GiB}, group_name="jb"))
        cache = build_cache(
            queues=[Queue(name="qa", weight=1), Queue(name="qb", weight=1)],
            pod_groups=[
                PodGroup(name="ja", namespace="c1", min_member=1, queue="qa"),
                PodGroup(name="jb", namespace="c1", min_member=1, queue="qb"),
            ],
            nodes=[build_node("n1", cpu=4000, mem=16 * GiB)],
            pods=pods,
        )
        run_actions(cache, action_names=["reclaim"])
        # qb deserves 1000m (its request caps it); exactly one eviction
        assert len(cache.evictor.evicts) == 1


class TestInterPodAffinity:
    def test_pod_affinity_co_locates(self):
        """e2e predicates.go:112 "Pod Affinity": a pod with required pod
        affinity lands in the same topology domain as the matching pod;
        the group's first pod passes via the affinity-only fast path."""
        from kube_batch_tpu.api.pod import Affinity, PodAffinityTerm
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pga", namespace="c1", min_member=1,
                                 queue="default"),
                        PodGroup(name="pgb", namespace="c1", min_member=1,
                                 queue="default")],
            nodes=[build_node(f"n{i}", cpu=8000, mem=16 * GiB) for i in range(4)],
            pods=[
                build_pod("c1", "anchor", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="pga",
                          labels={"app": "db"}),
                build_pod("c1", "follower", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="pgb",
                          affinity=Affinity(pod_affinity=[
                              PodAffinityTerm(match_labels={"app": "db"})])),
            ],
        )
        run_actions(cache, action_names=["allocate"])
        binds = cache.binder.binds
        assert binds["c1/anchor"] == binds["c1/follower"]

    def test_pod_anti_affinity_spreads(self):
        """e2e-style anti-affinity: two pods with the same label and
        hostname-scope anti-affinity must land on different nodes."""
        from kube_batch_tpu.api.pod import Affinity, PodAffinityTerm
        anti = Affinity(pod_anti_affinity=[PodAffinityTerm(match_labels={"app": "w"})])
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg", namespace="c1", min_member=2,
                                 queue="default")],
            nodes=[build_node(f"n{i}", cpu=8000, mem=16 * GiB) for i in range(3)],
            pods=[
                build_pod("c1", "w-0", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="pg",
                          labels={"app": "w"}, affinity=anti),
                build_pod("c1", "w-1", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="pg",
                          labels={"app": "w"}, affinity=anti),
            ],
        )
        run_actions(cache, action_names=["allocate"])
        binds = cache.binder.binds
        assert len(binds) == 2
        assert binds["c1/w-0"] != binds["c1/w-1"]

    def test_anti_affinity_against_running_pod(self):
        """Anti-affinity vs an already-running pod in the same domain."""
        from kube_batch_tpu.api.pod import Affinity, PodAffinityTerm
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg", namespace="c1", min_member=1,
                                 queue="default")],
            nodes=[build_node("n0", cpu=8000, mem=16 * GiB),
                   build_node("n1", cpu=8000, mem=16 * GiB)],
            pods=[
                build_pod("c1", "existing", "n0", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, labels={"app": "x"}),
                build_pod("c1", "new", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="pg",
                          affinity=Affinity(pod_anti_affinity=[
                              PodAffinityTerm(match_labels={"app": "x"})])),
            ],
        )
        run_actions(cache, action_names=["allocate"])
        assert cache.binder.binds["c1/new"] == "n1"

    def test_zone_topology_affinity(self):
        """Non-hostname topology key: domain = nodes sharing the zone label."""
        from kube_batch_tpu.api.pod import Affinity, PodAffinityTerm
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg", namespace="c1", min_member=1,
                                 queue="default")],
            nodes=[build_node("n0", cpu=8000, mem=16 * GiB, labels={"zone": "a"}),
                   build_node("n1", cpu=8000, mem=16 * GiB, labels={"zone": "a"}),
                   build_node("n2", cpu=8000, mem=16 * GiB, labels={"zone": "b"})],
            pods=[
                build_pod("c1", "anchor", "n0", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, labels={"app": "db"}),
                build_pod("c1", "near", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="pg",
                          affinity=Affinity(pod_affinity=[
                              PodAffinityTerm(match_labels={"app": "db"},
                                              topology_key="zone")])),
            ],
        )
        run_actions(cache, action_names=["allocate"])
        assert cache.binder.binds["c1/near"] in ("n0", "n1")  # zone a only


class TestPreferredAffinity:
    def test_preferred_node_affinity_steers(self):
        """e2e nodeorder.go "Node Affinity" (:29): a preferred term steers
        placement toward the matching node without excluding others."""
        from kube_batch_tpu.api.pod import Affinity
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg", namespace="c1", min_member=1,
                                 queue="default")],
            nodes=[build_node("plain", cpu=8000, mem=16 * GiB),
                   build_node("ssd", cpu=8000, mem=16 * GiB,
                              labels={"disk": "ssd"})],
            pods=[build_pod("c1", "p", None, PodPhase.PENDING,
                            {"cpu": 1000, "memory": GiB}, group_name="pg",
                            affinity=Affinity(preferred_node_terms=[
                                (50.0, [("disk", "In", ("ssd",))])]))],
        )
        run_actions(cache, action_names=["allocate"])
        assert cache.binder.binds["c1/p"] == "ssd"

    def test_preferred_pod_affinity_co_locates(self):
        """e2e nodeorder.go "Pod Affinity" (:74): soft co-location."""
        from kube_batch_tpu.api.pod import Affinity, PodAffinityTerm
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg", namespace="c1", min_member=1,
                                 queue="default")],
            nodes=[build_node(f"n{i}", cpu=8000, mem=16 * GiB) for i in range(4)],
            pods=[
                build_pod("c1", "anchor", "n2", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, labels={"app": "db"}),
                build_pod("c1", "near", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="pg",
                          affinity=Affinity(preferred_pod_affinity=[
                              (50.0, PodAffinityTerm(match_labels={"app": "db"}))])),
            ],
        )
        run_actions(cache, action_names=["allocate"])
        assert cache.binder.binds["c1/near"] == "n2"

    def test_preferred_pod_anti_affinity_avoids(self):
        from kube_batch_tpu.api.pod import Affinity, PodAffinityTerm
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg", namespace="c1", min_member=1,
                                 queue="default")],
            nodes=[build_node("n0", cpu=8000, mem=16 * GiB),
                   build_node("n1", cpu=8000, mem=16 * GiB)],
            pods=[
                build_pod("c1", "noisy", "n0", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, labels={"app": "noisy"}),
                build_pod("c1", "quiet", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="pg",
                          affinity=Affinity(preferred_pod_anti_affinity=[
                              (50.0, PodAffinityTerm(match_labels={"app": "noisy"}))])),
            ],
        )
        run_actions(cache, action_names=["allocate"])
        assert cache.binder.binds["c1/quiet"] == "n1"


class TestVolumeScenarios:
    """Standalone PV ledger behind the VolumeBinder seam (cache/volume.py;
    cache.go:189-209, 258-269 — AllocateVolumes can fail a node,
    BindVolumes consumes)."""

    def _cache_with_pv_binder(self, **kw):
        return _cache_with_pv_binder(**kw)

    def test_node_without_required_volume_is_skipped(self):
        """A pod claiming a node-local PV must land on the PV's node even
        when another node scores equally on resources."""
        from kube_batch_tpu.api.pod import PersistentVolume

        cache = self._cache_with_pv_binder(
            queues=["default"],
            nodes=[build_node("n1", cpu=8000, mem=16 * GiB),
                   build_node("n2", cpu=8000, mem=16 * GiB)],
            pods=[build_pod("c1", "dbpod", None, PodPhase.PENDING,
                            {"cpu": 1000, "memory": GiB},
                            volume_claims=("data-claim",))],
        )
        cache.volume_binder.add_pv(
            PersistentVolume(name="pv-local", node="n2", claim="data-claim"))
        run_actions(cache, action_names=["allocate"])
        assert cache.binder.binds["c1/dbpod"] == "n2"
        # the binding became durable at dispatch (BindVolumes)
        assert cache.volume_binder.bound == {"data-claim": "pv-local"}
        assert cache.volume_binder.reservations == {}

    def test_unsatisfiable_claim_fails_placement(self):
        from kube_batch_tpu.api.pod import PersistentVolume

        cache = self._cache_with_pv_binder(
            queues=["default"],
            nodes=[build_node("n1", cpu=8000, mem=16 * GiB)],
            pods=[build_pod("c1", "dbpod", None, PodPhase.PENDING,
                            {"cpu": 1000, "memory": GiB},
                            volume_claims=("ghost-claim",))],
        )
        cache.volume_binder.add_pv(
            PersistentVolume(name="pv-other", node="n1", claim="someone-else"))
        run_actions(cache, action_names=["allocate"])
        assert "c1/dbpod" not in cache.binder.binds

    def test_two_claimants_one_pv(self):
        """Two pods wanting the same pre-bound claim volume: exactly one may
        hold it (second claimant of the same PVC is a config error upstream;
        the ledger must still never double-book a PV)."""
        from kube_batch_tpu.api.pod import PersistentVolume

        cache = self._cache_with_pv_binder(
            queues=["default"],
            nodes=[build_node("n1", cpu=8000, mem=16 * GiB)],
            pods=[
                build_pod("c1", "a", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB},
                          volume_claims=("claim-a",)),
                build_pod("c1", "b", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB},
                          volume_claims=("claim-b",)),
            ],
        )
        # single wildcard PV: only one claim can take it
        cache.volume_binder.add_pv(PersistentVolume(name="pv1"))
        run_actions(cache, action_names=["allocate"])
        placed = [k for k in ("c1/a", "c1/b") if k in cache.binder.binds]
        assert len(placed) == 1
        assert len(cache.volume_binder.bound) == 1

    def test_allocate_volumes_idempotent_per_task(self):
        """The bulk-path volume pre-check followed by a demoted job's
        sequential replay re-allocates the same task: must not double-book."""
        from kube_batch_tpu.api.pod import PersistentVolume, Pod
        from kube_batch_tpu.cache.volume import StandalonePVBinder
        from kube_batch_tpu.api.task_info import TaskInfo
        from kube_batch_tpu.api.resources import DEFAULT_SPEC

        binder = StandalonePVBinder()
        binder.add_pv(PersistentVolume(name="pv1"))
        binder.add_pv(PersistentVolume(name="pv2"))
        pod = Pod(name="p", namespace="c1", requests={"cpu": 100},
                  volume_claims=("c",))
        task = TaskInfo(pod, DEFAULT_SPEC)
        binder.allocate_volumes(task, "n1")
        binder.allocate_volumes(task, "n1")  # replay — same reservation
        assert len(binder.reservations) == 1
        assert len(binder.reservations[task.uid]) == 1
        binder.allocate_volumes(task, "n2")  # moved host — superseded
        assert len(binder.reservations[task.uid]) == 1
        binder.bind_volumes(task)
        assert len(binder.bound) == 1 and binder.reservations == {}


class TestPDBGang:
    """PodDisruptionBudget as the legacy gang source (event_handlers.go:
    484-594): pods sharing a controller + a PDB on that controller form a
    gang with the PDB's min-available, in the default queue, with
    events-only status (job_updater.go:108-111)."""

    def test_gang_defined_only_by_pdb_schedules(self):
        from kube_batch_tpu.api.pod import PodDisruptionBudget

        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1", cpu=4000, mem=8 * GiB)],
        )
        cache.add_pdb(PodDisruptionBudget(
            name="pdb1", namespace="c1", min_available=3, owner="rs-1"))
        for i in range(3):
            cache.add_pod(build_pod("c1", f"w{i}", None, PodPhase.PENDING,
                                    {"cpu": 1000, "memory": GiB}, owner="rs-1"))
        job = cache.jobs["c1/rs-1"]
        assert job.pdb is not None and job.pod_group is None
        assert job.min_available == 3 and job.queue == "default"
        run_actions(cache, action_names=["allocate"])
        assert len(cache.binder.binds) == 3

    def test_pdb_gang_blocks_partial_placement(self):
        from kube_batch_tpu.api.pod import PodDisruptionBudget

        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1", cpu=2000, mem=8 * GiB)],  # fits only 2
        )
        cache.add_pdb(PodDisruptionBudget(
            name="pdb1", namespace="c1", min_available=3, owner="rs-1"))
        for i in range(3):
            cache.add_pod(build_pod("c1", f"w{i}", None, PodPhase.PENDING,
                                    {"cpu": 1000, "memory": GiB}, owner="rs-1"))
        run_actions(cache, action_names=["allocate"])
        assert len(cache.binder.binds) == 0  # all-or-nothing gang
        # events-only status: an Unschedulable event was recorded, and no
        # PodGroup status write happened for the PDB job
        assert any(kind == "Unschedulable" and key == "c1/rs-1"
                   for kind, key, _ in cache.events)

    def test_delete_pdb_releases_gang(self):
        from kube_batch_tpu.api.pod import PodDisruptionBudget

        cache = build_cache(queues=["default"],
                            nodes=[build_node("n1", cpu=2000, mem=8 * GiB)])
        pdb = PodDisruptionBudget(
            name="pdb1", namespace="c1", min_available=3, owner="rs-1")
        cache.add_pdb(pdb)
        for i in range(3):
            cache.add_pod(build_pod("c1", f"w{i}", None, PodPhase.PENDING,
                                    {"cpu": 1000, "memory": GiB}, owner="rs-1"))
        cache.delete_pdb(pdb)
        job = cache.jobs["c1/rs-1"]
        assert job.pdb is None
        # the gang constraint is gone: the pods re-shadow as singletons and
        # now schedule individually (2 of 3 fit the 2000m node)
        assert job.pod_group is not None and job.pod_group.shadow
        assert job.min_available == 1
        run_actions(cache, action_names=["allocate"])
        assert len(cache.binder.binds) == 2

    def test_pods_before_pdb_ordering(self):
        """Owner pods ingested BEFORE their PDB: the synthesized shadow
        PodGroup must yield to the PDB as the gang source."""
        from kube_batch_tpu.api.pod import PodDisruptionBudget

        cache = build_cache(queues=["default"],
                            nodes=[build_node("n1", cpu=2000, mem=8 * GiB)])
        for i in range(3):
            cache.add_pod(build_pod("c1", f"w{i}", None, PodPhase.PENDING,
                                    {"cpu": 1000, "memory": GiB}, owner="rs-1"))
        job = cache.jobs["c1/rs-1"]
        assert job.pod_group is not None and job.pod_group.shadow
        cache.add_pdb(PodDisruptionBudget(
            name="pdb1", namespace="c1", min_available=3, owner="rs-1"))
        assert job.pod_group is None and job.pdb is not None
        assert job.min_available == 3
        run_actions(cache, action_names=["allocate"])
        assert len(cache.binder.binds) == 0  # gang of 3 can't fit 2 slots

    def test_discarded_gang_releases_pv_reservations(self):
        """A gang that can't fully place must not hold PV reservations
        across cycles (Statement discard releases assumed volumes), so other
        claimants of the same wildcard PV still schedule."""
        from kube_batch_tpu.api.pod import PersistentVolume

        cache = _cache_with_pv_binder(
            queues=["default"],
            pod_groups=[PodGroup(name="gang2", namespace="c1", min_member=2,
                                 queue="default")],
            nodes=[build_node("n1", cpu=8000, mem=16 * GiB)],
            pods=[
                # task A: satisfiable claim; task B: unsatisfiable → the
                # gang discards every cycle
                build_pod("c1", "a", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="gang2",
                          volume_claims=("claim-a",)),
                build_pod("c1", "b", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="gang2",
                          volume_claims=("ghost",)),
                # independent singleton wanting the same wildcard PV
                build_pod("c1", "solo", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB},
                          volume_claims=("claim-solo",)),
            ],
        )
        cache.volume_binder.add_pv(PersistentVolume(name="pv1"))
        run_actions(cache, action_names=["allocate"])
        assert "c1/a" not in cache.binder.binds  # gang blocked
        assert "c1/b" not in cache.binder.binds
        assert cache.binder.binds.get("c1/solo") == "n1"
        # no reservation lingers for the discarded gang
        assert cache.volume_binder.reservations == {}


class TestPreemptPhase2Divergence:
    """Pins the DECLARED divergence from the reference's preempt phase 2
    (PARITY.md "known divergences" / actions/preempt.py:104-131): the
    reference runs intra-job rebalancing unconditionally (preempt.go:145-174)
    and would evict an equal-rank running sibling to pipeline a pending one —
    zero-gain churn; this rebuild gates phase 2 on a task-order plugin
    verdict (or, with no voter, on the raw priority extremes) and SKIPS the
    equal-rank case. These tests pin both sides of the gate so a refactor
    cannot silently change the behavior."""

    def _cache(self, pending_priority):
        pods = [
            build_pod("c1", f"run-{i}", "n1", PodPhase.RUNNING,
                      {"cpu": 1000, "memory": GiB}, group_name="job",
                      priority=0)
            for i in range(2)
        ] + [
            build_pod("c1", "pend-0", None, PodPhase.PENDING,
                      {"cpu": 1000, "memory": GiB}, group_name="job",
                      priority=pending_priority)
        ]
        return build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="job", namespace="c1", min_member=1,
                                 queue="default")],
            nodes=[build_node("n1", cpu=2000, mem=16 * GiB)],  # full
            pods=pods,
        )

    def test_equal_rank_sibling_not_evicted(self):
        """The divergent case: the reference would evict a running sibling
        for the equal-priority pending task; the gate skips phase 2 and
        nothing happens."""
        cache = self._cache(pending_priority=0)
        run_actions(cache, action_names=["preempt"])
        assert len(cache.evictor.evicts) == 0
        assert len(cache.binder.binds) == 0

    def test_outranking_pending_task_preempts_sibling(self):
        """The gate's positive side (matching the reference): a pending task
        that outranks a running sibling via the priority plugin's task order
        evicts exactly one sibling and pipelines onto the freed capacity."""
        cache = self._cache(pending_priority=100)
        run_actions(cache, action_names=["preempt"])
        assert len(cache.evictor.evicts) == 1
        assert next(iter(cache.evictor.evicts)).startswith("c1/run-")
        # the preemptor pipelines (placed on Releasing capacity) — it binds
        # only after the eviction completes, so no bind yet this cycle
        assert len(cache.binder.binds) == 0

    def test_no_task_order_voter_falls_back_to_raw_priority(self):
        """With the priority plugin disabled (no task-order voter), the gate
        falls back to comparing raw priority extremes — still skipping the
        equal-rank case."""
        conf_no_priority = """
actions: "preempt"
tiers:
- plugins:
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: proportion
  - name: nodeorder
  - name: predicates
"""
        cache = self._cache(pending_priority=0)
        run_actions(cache, conf_text=conf_no_priority,
                    action_names=["preempt"])
        assert len(cache.evictor.evicts) == 0

    def test_reference_exact_restores_ungated_phase2(self):
        """`preempt.referenceExact: "true"` on any conf tier restores
        preempt.go:145-174's unconditional phase 2: the equal-rank pending
        sibling DOES evict a running one (the churn the gate avoids)."""
        conf_exact = """
actions: "preempt"
tiers:
- plugins:
  - name: priority
    arguments:
      preempt.referenceExact: "true"
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: proportion
  - name: nodeorder
  - name: predicates
"""
        cache = self._cache(pending_priority=0)
        run_actions(cache, conf_text=conf_exact, action_names=["preempt"])
        assert len(cache.evictor.evicts) == 1
        assert next(iter(cache.evictor.evicts)).startswith("c1/run-")


class TestReclaimReferenceExact:
    """`reclaim.referenceExact: "true"` disables the idle-fit claimant gate
    (the PARITY.md reclaim divergence): like reclaim.go:107-199, a
    cross-queue victim is evicted even when free capacity could satisfy the
    claimant."""

    def _cache(self):
        from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Node, Pod

        cache = build_cache(queues=[])
        from kube_batch_tpu.api.pod import Queue

        cache.add_queue(Queue(name="q0", weight=1))
        cache.add_queue(Queue(name="q1", weight=3))
        # free cpu for the claimant AND a cross-queue victim on the node
        cache.add_node(Node(name="n1", allocatable={
            "cpu": 4000.0, "memory": float(64 * GiB), "pods": 110.0}))
        cache.add_pod_group(PodGroup(name="r", namespace="b", min_member=1,
                                     queue="q0", creation_index=0))
        cache.add_pod(Pod(name="r", namespace="b",
                          requests={"cpu": 1000.0, "memory": float(GiB)},
                          annotations={GROUP_NAME_ANNOTATION: "r"},
                          phase=PodPhase.RUNNING, node_name="n1",
                          creation_index=0))
        cache.add_pod_group(PodGroup(name="p", namespace="b", min_member=1,
                                     queue="q1", creation_index=1))
        cache.add_pod(Pod(name="p", namespace="b",
                          requests={"cpu": 1000.0, "memory": float(GiB)},
                          annotations={GROUP_NAME_ANNOTATION: "p"},
                          phase=PodPhase.PENDING, creation_index=1))
        return cache

    CONF = """
actions: "reclaim, allocate, backfill"
tiers:
- plugins:
  - name: priority
{ARG}
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: proportion
  - name: nodeorder
  - name: predicates
"""

    def _run(self, cache, exact: bool):
        from kube_batch_tpu.framework.conf import parse_scheduler_conf
        from kube_batch_tpu.scheduler import Scheduler

        arg = ('    arguments:\n'
               '      reclaim.referenceExact: "true"') if exact else ""
        conf = parse_scheduler_conf(self.CONF.replace("{ARG}", arg))
        sched = Scheduler(cache, conf=conf)
        sched.run_once()
        cache.flush_binds()

    def test_gate_on_no_eviction(self):
        """Default: the claimant fits idle, so allocate places it and the
        victim survives (the declared improvement)."""
        cache = self._cache()
        self._run(cache, exact=False)
        assert not cache.evictor.evicts
        assert "b/p" in cache.binder.binds

    def test_reference_exact_evicts_like_the_reference(self):
        """With the escape hatch, reclaim evicts the cross-queue victim for
        the claimant even though free capacity could satisfy it —
        reclaim.go's exact behavior."""
        cache = self._cache()
        self._run(cache, exact=True)
        assert "b/r" in cache.evictor.evicts, cache.evictor.evicts


class TestRealRequestBackfill:
    """BEYOND-REFERENCE (backfill.go:87's own TODO): real-request tasks fill
    capacity stranded by host-side gang discards.  The batched solve gave
    the capacity to gang G; G's volume claims failed host-side and its
    Statement discarded, leaving the freed capacity stranded for the rest
    of the cycle.  The reference's backfill (BestEffort-only) could never
    perform this fill; ours re-solves over gang-safe claimants."""

    def _cache(self):
        pods = []
        # gang G: 4 x 1000m with unsatisfiable volume claims — the device
        # places it, the host volume pre-check demotes, the slow replay
        # discards (no PV exists anywhere)
        for i in range(4):
            pods.append(build_pod(
                "c1", f"g-{i}", None, PodPhase.PENDING,
                {"cpu": 1000, "memory": GiB}, group_name="g",
                volume_claims=("no-such-pv",),
            ))
        # singleton S, created later (worse rank): crowded out by G in the
        # main solve
        pods.append(build_pod("c1", "s-0", None, PodPhase.PENDING,
                              {"cpu": 1000, "memory": GiB}, group_name="s"))
        return _cache_with_pv_binder(
            queues=["default"],
            pod_groups=[
                PodGroup(name="g", namespace="c1", min_member=4,
                         queue="default", creation_index=1),
                PodGroup(name="s", namespace="c1", min_member=1,
                         queue="default", creation_index=2),
            ],
            nodes=[build_node("n1", cpu=4000, mem=16 * GiB)],
            pods=pods,
        )

    def test_stranded_capacity_backfilled(self):
        cache = self._cache()
        ssn = run_actions(cache, action_names=["allocate", "backfill"])
        from kube_batch_tpu.framework.interface import get_action

        assert get_action("allocate").last_host_discards == 1
        # the control signal backfill consumed rides the SESSION, not the
        # process-global action registry (ADVICE.md #5) — ≥1 because the
        # backfill helper replay's own discards accumulate on it too
        assert ssn.host_discards >= 1
        # G discarded entirely; S backfilled into the freed capacity
        assert set(cache.binder.binds) == {"c1/s-0"}
        assert not cache.evictor.evicts
        errs = cache.columns.check_consistency(cache)
        assert not errs, errs[:3]

    def test_flag_off_leaves_capacity_stranded(self):
        """`backfill.realRequests: "false"` restores the reference-shaped
        behavior: the stranded task waits for the next cycle."""
        conf_off = """
actions: "allocate, backfill"
tiers:
- plugins:
  - name: priority
    arguments:
      backfill.realRequests: "false"
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: proportion
  - name: nodeorder
  - name: predicates
"""
        cache = self._cache()
        run_actions(cache, conf_text=conf_off,
                    action_names=["allocate", "backfill"])
        assert not cache.binder.binds  # s-0 stranded until next cycle
