"""ColumnStore consistency: the persistent columnar host model must agree
with the object model after ingest, scheduling cycles, evictions, churn,
and axis growth (api/columns.py check_consistency)."""

import numpy as np

from kube_batch_tpu import actions as _actions  # noqa: F401 — registers
from kube_batch_tpu import plugins as _plugins  # noqa: F401 — registers
from kube_batch_tpu.api.pod import GROUP_NAME_ANNOTATION, Node, Pod, PodGroup, PriorityClass
from kube_batch_tpu.api.types import PodPhase, TaskStatus
from kube_batch_tpu.framework.conf import parse_scheduler_conf
from kube_batch_tpu.scheduler import Scheduler

from tests.fixtures import GiB, build_cache, build_node, build_pod

FULL_CONF = """
actions: "enqueue, reclaim, allocate, backfill, preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def _soak_add_gang(cache, rng, next_id, queues=("default",),
                   cpu_choices=(250, 500, 1000), prio_choices=(0,)):
    """Shared gang generator for the churn soaks: a random-size PodGroup in
    a random queue with random per-task cpu and priority."""
    g = next_id[0]
    next_id[0] += 1
    size = int(rng.integers(1, 4))
    queue = queues[int(rng.integers(len(queues)))]
    cache.add_pod_group(PodGroup(
        name=f"g{g}", namespace="c", min_member=size, queue=queue,
        creation_index=g,
    ))
    prio = int(rng.choice(prio_choices))
    for i in range(size):
        cache.add_pod(Pod(
            name=f"g{g}-{i}", namespace="c",
            requests={"cpu": float(rng.choice(cpu_choices)),
                      "memory": float(GiB)},
            annotations={GROUP_NAME_ANNOTATION: f"g{g}"},
            priority=prio,
            creation_index=g * 10 + i,
        ))


def assert_consistent(cache):
    errs = cache.columns.check_consistency(cache)
    assert not errs, "\n".join(errs)


class TestColumnConsistency:
    def test_ingest_and_cycle(self):
        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg1", namespace="c1", min_member=3, queue="default")],
            nodes=[build_node("n1", cpu=4000, mem=8 * GiB),
                   build_node("n2", cpu=4000, mem=8 * GiB)],
            pods=[
                build_pod("c1", f"p{i}", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="pg1")
                for i in range(3)
            ] + [build_pod("c1", "solo", None, PodPhase.PENDING,
                           {"cpu": 500, "memory": GiB})],
        )
        assert_consistent(cache)
        sched = Scheduler(cache)
        sched.run_once()
        assert_consistent(cache)
        assert len(cache.binder.binds) == 4

    def test_churn_and_growth(self):
        """Enough pods to force several task-axis growths + delete/re-add
        churn so rows are freed and reused."""
        cache = build_cache(
            queues=["default"],
            nodes=[build_node(f"n{i}", cpu=64000, mem=64 * GiB, pods=200)
                   for i in range(4)],
            pods=[],
        )
        for i in range(40):
            cache.add_pod(build_pod("c1", f"p{i}", None, PodPhase.PENDING,
                                    {"cpu": 100, "memory": GiB // 8}))
        assert_consistent(cache)
        # delete half (frees rows), re-add with different requests
        for i in range(0, 40, 2):
            cache.delete_pod(cache.pods[f"c1/p{i}"])
        assert_consistent(cache)
        for i in range(40, 120):
            cache.add_pod(build_pod("c1", f"p{i}", None, PodPhase.PENDING,
                                    {"cpu": 200, "memory": GiB // 4}))
        assert_consistent(cache)
        sched = Scheduler(cache)
        sched.run_once()
        assert_consistent(cache)
        # every pending pod fit
        assert len(cache.binder.binds) == 100

    def test_full_pipeline_with_eviction(self):
        """Eviction flows (preempt) + kubelet sim keep columns in sync."""
        cache = build_cache(
            queues=["default"],
            pod_groups=[
                PodGroup(name="low", namespace="c1", min_member=1, queue="default"),
                PodGroup(name="high", namespace="c1", min_member=1, queue="default",
                         priority_class="high-prio"),
            ],
            nodes=[build_node("n1", cpu=2000, mem=4 * GiB, pods=10)],
            pods=[
                build_pod("c1", "low-1", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
                build_pod("c1", "low-2", "n1", PodPhase.RUNNING,
                          {"cpu": 1000, "memory": GiB}, group_name="low"),
                build_pod("c1", "high-1", None, PodPhase.PENDING,
                          {"cpu": 1000, "memory": GiB}, group_name="high",
                          priority=100),
            ],
        )
        cache.add_priority_class(PriorityClass(name="high-prio", value=100))
        conf = parse_scheduler_conf(FULL_CONF)
        sched = Scheduler(cache, conf=conf)
        sched.run_once()
        assert_consistent(cache)
        assert len(cache.evictor.evicts) == 1
        cache.delete_pod(cache.pods[cache.evictor.evicts[0]])
        assert_consistent(cache)
        sched.run_once()
        cache.flush_binds()
        assert cache.binder.binds.get("c1/high-1") == "n1"
        assert_consistent(cache)

    def test_node_update_and_labels(self):
        """set_node on a bound node rewrites ledger views in place and
        re-interns labels; late-arriving labels un-impossible selectors."""
        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1", cpu=4000, mem=8 * GiB)],
            pods=[build_pod("c1", "sel", None, PodPhase.PENDING,
                            {"cpu": 500, "memory": GiB},
                            node_selector={"zone": "a"})],
        )
        sched = Scheduler(cache)
        sched.run_once()
        assert cache.binder.binds == {}  # no node carries zone=a yet
        assert_consistent(cache)
        # node gains the label → selector becomes satisfiable
        cache.add_node(Node(name="n1", allocatable={"cpu": 4000,
                                                    "memory": 8 * GiB,
                                                    "pods": 110},
                            labels={"zone": "a"}))
        sched.run_once()
        cache.flush_binds()
        assert cache.binder.binds == {"c1/sel": "n1"}
        assert_consistent(cache)

    def test_node_delete_with_residents_demotes_then_retires(self):
        """Deleting a node with resident bound pods keeps them registered on
        a nodeless placeholder (zero capacity, excluded from snapshots) — a
        re-added node replays their accounting via set_node, and a kubelet
        update can't re-account a task into fresh capacity (the underflow
        the 150-cycle soak caught). The placeholder retires with its last
        resident, freeing the row with no task aliasing it."""
        cols_pods = [build_pod("c1", "resident", "n1", PodPhase.RUNNING,
                               {"cpu": 500, "memory": GiB})]
        cache = build_cache(
            queues=["default"], nodes=[build_node("n1")], pods=cols_pods,
        )
        cols = cache.columns
        cache.delete_node("n1")
        # demoted, not freed: resident stays attached, node leaves snapshots
        node = cache.nodes["n1"]
        assert node.node is None and "c1/resident" in node.tasks
        assert not cols.n_valid[node._row]
        assert (node.allocatable.vec == 0).all()
        assert_consistent(cache)
        # re-add: accounting replays (underflow-free), pod still resident
        cache.add_node(build_node("n1", cpu=4000, mem=8 * GiB))
        node = cache.nodes["n1"]
        assert node.node is not None
        assert node.idle.milli_cpu == 3500.0
        assert_consistent(cache)
        # delete again, then the resident dies → placeholder retires
        cache.delete_node("n1")
        row = cache.nodes["n1"]._row
        cache.delete_pod(cache.pods["c1/resident"])
        assert "n1" not in cache.nodes
        assert not (cols.t_node == row).any()
        cache.add_node(build_node("n2"))  # may reuse the freed row
        row2 = cols.node_rows["n2"]
        assert not (cols.t_node == row2).any()
        assert_consistent(cache)

    def test_node_delete_without_residents_frees_row(self):
        cache = build_cache(queues=["default"], nodes=[build_node("n1")],
                            pods=[])
        cols = cache.columns
        row = cols.node_rows["n1"]
        live_before = cols.nodes.n_live
        cache.delete_node("n1")
        assert "n1" not in cache.nodes
        assert "n1" not in cols.node_rows  # the COLUMN row was freed too
        assert cols.nodes.n_live == live_before - 1
        assert not cols.n_valid[row]
        assert_consistent(cache)

    def test_allocate_action_picks_sharded_path(self):
        """VERDICT r2 #3: on a multi-device part with a big-enough node
        axis, the production AllocateAction must dispatch the mesh-sharded
        solve — and produce correct bindings through it."""
        import jax

        from kube_batch_tpu.framework.interface import get_action
        from kube_batch_tpu.parallel.mesh import SHARD_MIN_NODES

        if len(jax.devices()) < 2:
            import pytest

            pytest.skip("needs the multi-device virtual mesh")
        n_nodes = 200  # node axis pads to 256 == SHARD_MIN_NODES
        cache = build_cache(
            queues=["default"],
            nodes=[build_node(f"n{i}") for i in range(n_nodes)],
            pods=[build_pod("c1", f"p{i}", None, PodPhase.PENDING,
                            {"cpu": 500, "memory": GiB}) for i in range(4)],
        )
        sched = Scheduler(cache)
        sched.run_once()
        cache.flush_binds()
        action = get_action("allocate")
        assert action.last_solve_mode == "sharded", action.last_solve_mode
        assert len(cache.binder.binds) == 4
        assert_consistent(cache)

    def test_node_delete_readd_keeps_resident_accounted(self):
        """A re-added node replays its surviving residents' accounting
        immediately (the delete demoted, not orphaned, them) — there is no
        window where bound capacity reads as free (the 150-cycle soak's
        underflow: the scheduler filled the 'free' capacity, then the pod's
        next event re-accounted it). A later pod update must not
        double-account either."""
        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1")],
            pods=[build_pod("c1", "res", "n1", PodPhase.RUNNING,
                            {"cpu": 500, "memory": GiB})],
        )
        task = cache.jobs["c1/res"].tasks["c1/res"]
        row = task._row
        cache.delete_node("n1")
        cache.add_node(build_node("n1"))
        node = cache.nodes["n1"]
        assert int(cache.columns.t_node[row]) == node._row
        assert "c1/res" in node.tasks
        idle_cpu = node.idle.milli_cpu
        assert idle_cpu == node.allocatable.milli_cpu - 500
        assert_consistent(cache)
        # the pod's next event (informer resync analog) is idempotent
        cache.update_pod(cache.pods["c1/res"])
        node = cache.nodes["n1"]
        assert node.idle.milli_cpu == idle_cpu
        assert "c1/res" in node.tasks
        assert_consistent(cache)

    def test_randomized_churn_soak(self):
        """Seeded soak: many cycles of random adds / deletes / updates /
        node churn / kubelet transitions, asserting full column/object
        consistency after every cycle.  The strongest drift guard the
        columnar model has — any missed choke point shows up here."""
        rng = np.random.default_rng(7)
        cache = build_cache(
            queues=["default"],
            nodes=[build_node(f"n{i}", cpu=8000, mem=16 * GiB, pods=30)
                   for i in range(6)],
            pods=[],
        )
        sched = Scheduler(cache)
        next_id = [0]

        def add_gang():
            _soak_add_gang(cache, rng, next_id)

        for cycle in range(25):
            op = rng.random()
            if op < 0.5:
                add_gang()
            elif op < 0.7 and cache.pods:
                # kubelet: a bound pod starts running, or a pod dies
                key = list(cache.pods)[int(rng.integers(len(cache.pods)))]
                pod = cache.pods[key]
                if pod.node_name and rng.random() < 0.6:
                    upd = Pod(
                        name=pod.name, namespace=pod.namespace, uid=pod.uid,
                        requests=dict(pod.requests), node_name=pod.node_name,
                        phase=PodPhase.RUNNING,
                        annotations=dict(pod.annotations),
                        creation_index=pod.creation_index,
                    )
                    cache.update_pod(upd)
                else:
                    cache.delete_pod(pod)
            elif op < 0.8:
                # node churn: delete or (re-)add
                name = f"n{int(rng.integers(6))}"
                if name in cache.nodes and rng.random() < 0.5:
                    cache.delete_node(name)
                else:
                    cache.add_node(build_node(name, cpu=8000, mem=16 * GiB,
                                              pods=30))
            # else: idle cycle
            sched.run_once()
            cache.flush_binds()
            errs = cache.columns.check_consistency(cache)
            assert not errs, (cycle, errs[:5])
        # the soak actually scheduled things
        assert len(cache.binder.binds) > 10

    def test_persistence_roundtrip_columns(self):
        """--state-file save/restore rebuilds a consistent column store and
        the restored cache schedules."""
        import os
        import tempfile

        from kube_batch_tpu.cache.cache import SchedulerCache
        from kube_batch_tpu.cache.persistence import load_state, save_state

        cache = build_cache(
            queues=["default"],
            pod_groups=[PodGroup(name="pg", namespace="c1", min_member=2,
                                 queue="default")],
            nodes=[build_node("n1"), build_node("n2")],
            pods=[
                build_pod("c1", "bound", "n1", PodPhase.RUNNING,
                          {"cpu": 500, "memory": GiB}),
                build_pod("c1", "g-0", None, PodPhase.PENDING,
                          {"cpu": 500, "memory": GiB}, group_name="pg"),
                build_pod("c1", "g-1", None, PodPhase.PENDING,
                          {"cpu": 500, "memory": GiB}, group_name="pg"),
            ],
        )
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "state.json")
            save_state(cache, path)
            restored = SchedulerCache()
            load_state(restored, path)
        assert_consistent(restored)
        assert restored.nodes["n1"].used.milli_cpu == 500.0
        Scheduler(restored).run_once()
        restored.flush_binds()
        assert set(restored.binder.binds) == {"c1/g-0", "c1/g-1"}
        assert_consistent(restored)

    def test_rebuild_from_pod_store(self):
        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1")],
            pods=[build_pod("c1", "a", "n1", PodPhase.RUNNING,
                            {"cpu": 500, "memory": GiB}),
                  build_pod("c1", "b", None, PodPhase.PENDING,
                            {"cpu": 500, "memory": GiB})],
        )
        cache.rebuild_from_pod_store()
        assert_consistent(cache)
        idle = cache.nodes["n1"].idle
        assert idle.milli_cpu == cache.nodes["n1"].allocatable.milli_cpu - 500


class TestFullPipelineChurnSoak:
    def test_five_action_churn_soak(self):
        """Seeded soak over the SHIPPED 5-action pipeline (enqueue, reclaim,
        allocate, backfill, preempt) with two weighted queues, random
        priorities, kubelet transitions (run / die / honor evictions), and
        node churn — after every cycle: full column/object consistency and
        the node resource algebra invariants (never overcommit, reclaim's
        and preempt's evictions included)."""
        conf = parse_scheduler_conf(FULL_CONF)
        rng = np.random.default_rng(11)
        from kube_batch_tpu.api.pod import Queue

        cache = build_cache(
            queues=[Queue(name="qa", weight=3), Queue(name="qb", weight=1)],
            nodes=[build_node(f"n{i}", cpu=6000, mem=16 * GiB, pods=30)
                   for i in range(4)],
            pods=[],
        )
        sched = Scheduler(cache, conf=conf)
        next_id = [0]

        def add_gang():
            _soak_add_gang(cache, rng, next_id, queues=("qa", "qb"),
                           cpu_choices=(500, 1000, 2000),
                           prio_choices=(0, 0, 0, 100))

        quanta = cache.spec.quanta
        for cycle in range(30):
            op = rng.random()
            if op < 0.45:
                add_gang()
            elif op < 0.65 and cache.pods:
                key = list(cache.pods)[int(rng.integers(len(cache.pods)))]
                pod = cache.pods[key]
                if pod.node_name and rng.random() < 0.7:
                    cache.update_pod(Pod(
                        name=pod.name, namespace=pod.namespace, uid=pod.uid,
                        requests=dict(pod.requests), node_name=pod.node_name,
                        phase=PodPhase.RUNNING,
                        annotations=dict(pod.annotations),
                        priority=pod.priority,
                        creation_index=pod.creation_index,
                    ))
                else:
                    cache.delete_pod(pod)
            elif op < 0.75:
                name = f"n{int(rng.integers(4))}"
                if name in cache.nodes and rng.random() < 0.5:
                    cache.delete_node(name)
                else:
                    cache.add_node(build_node(name, cpu=6000, mem=16 * GiB,
                                              pods=30))
            # honor pending evictions like a kubelet: terminate the pods the
            # evictor asked for, so Releasing capacity actually frees
            for key in list(cache.evictor.evicts):
                pod = cache.pods.get(key)
                if pod is not None:
                    cache.delete_pod(pod)
            cache.evictor.evicts.clear()

            sched.run_once()
            cache.flush_binds()
            errs = cache.columns.check_consistency(cache)
            assert not errs, (cycle, errs[:5])
            for node in cache.nodes.values():
                assert (node.idle.vec >= -quanta).all(), (cycle, node.name)
                assert (node.used.vec
                        <= node.allocatable.vec + quanta).all(), (
                    cycle, node.name)
        assert len(cache.binder.binds) > 10


class TestResidentFeatureCache:
    def test_reuse_and_invalidation(self):
        """resident_features returns the SAME device arrays while the
        feature_version is unchanged, refreshes after ingest (bind/free
        task, node meta change), and the refreshed upload carries the new
        values — the staleness hazard the version counter exists for."""
        import numpy as np

        from kube_batch_tpu.framework.conf import load_scheduler_conf
        from kube_batch_tpu.framework.session import close_session, open_session

        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1", cpu=4000, mem=8 * GiB)],
            pods=[build_pod("c", "p0", None, PodPhase.PENDING,
                            {"cpu": 1000, "memory": GiB}, group_name="g0")],
            pod_groups=[PodGroup(name="g0", namespace="c", min_member=1,
                                 queue="default")],
        )
        cols = cache.columns
        conf = load_scheduler_conf(None)
        ssn = open_session(cache, conf.tiers)
        try:
            snap, _meta = cols.device_snapshot(ssn)
            r1 = cols.resident_features(snap)
            r2 = cols.resident_features(snap)
            assert r1.task_req is r2.task_req  # cached, no re-upload
            assert r1.node_alloc is r2.node_alloc
            np.testing.assert_array_equal(
                np.asarray(r1.task_req), cols.t_init32)
        finally:
            close_session(ssn)
        # ingest invalidates: a new task must appear in the next upload
        v0 = cols.task_feature_version
        cache.add_pod_group(PodGroup(name="g1", namespace="c", min_member=1,
                                     queue="default"))
        cache.add_pod(build_pod("c", "p1", None, PodPhase.PENDING,
                                {"cpu": 2000, "memory": GiB},
                                group_name="g1"))
        assert cols.task_feature_version > v0
        ssn = open_session(cache, conf.tiers)
        try:
            snap2, meta2 = cols.device_snapshot(ssn)
            r3 = cols.resident_features(snap2)
            assert r3.task_req is not r1.task_req
            np.testing.assert_array_equal(
                np.asarray(r3.task_req), cols.t_init32)
            # node meta change (labels) invalidates node bits
            prev_bits = r3.node_label_bits
            node = cache.nodes["n1"]
            obj = build_node("n1", cpu=4000, mem=8 * GiB,
                             labels={"zone": "z1"})
            node.set_node(obj)
            snap3, _ = cols.device_snapshot(ssn)
            r4 = cols.resident_features(snap3)
            assert r4.node_label_bits is not prev_bits
        finally:
            close_session(ssn)

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv("KB_DEVICE_CACHE", "0")
        from kube_batch_tpu.framework.conf import load_scheduler_conf
        from kube_batch_tpu.framework.session import close_session, open_session

        cache = build_cache(
            queues=["default"],
            nodes=[build_node("n1", cpu=4000, mem=8 * GiB)],
            pods=[],
        )
        cols = cache.columns
        conf = load_scheduler_conf(None)
        ssn = open_session(cache, conf.tiers)
        try:
            snap, _ = cols.device_snapshot(ssn)
            assert cols.resident_features(snap) is snap
        finally:
            close_session(ssn)
